"""Scan-fused checkpoint windows + fleet-shared AOT executables
(parallel/sweep.py scan_window, engine/core.py build_window_runner,
parallel/aot.py).

The contracts under test:

* the scan-fused window path (W segments folded into ONE device call,
  liveness carried through the scan and fetched once per window)
  produces **byte-identical** ``LaneResults`` to the serial segment
  loop (``scan_window=1``) — dead tail iterations are fixed-point
  no-ops — composing with ``pipeline_depth`` and narrowing;
* host round-trips really drop from per-segment to per-window
  (``parallel.sweep.LAST_STATS`` device-call accounting — the live
  twin of bench.py's ``window_roundtrips``), and the early-exit
  overshoot a finished batch pays is bounded by W no-op segments per
  in-flight window (the window-granular liveness bound that replaced
  the segment loop's ``pipeline_depth − 1``);
* checkpoints are **window-size-free** (like ``pipeline_depth`` and
  ``mesh_shard``, the window is deliberately not a manifest meta key):
  a run interrupted under one ``scan_window`` resumes under any other
  bit-exactly, and a kill mid-window loses at most one window;
* AOT round-trip: a sweep executable serialized by one process loads
  in a FRESH subprocess (no trace, ``aot-load`` provenance) and runs
  byte-identical to the traced control; signature drift and payload
  corruption are refused by name (``AotMismatchError``), and on the
  pinned jaxlib the AOT runner is forced undonated
  (``engine/core.py aot_donation_safe`` — a donated deserialized
  executable is known to corrupt).

Tier-1 pins tempo + basic; the full protocol matrix rides in the slow
tier.
"""

import json
import math
import os
import shutil
import subprocess
import sys

import pytest

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.checkpoint import (
    CheckpointSpec,
    SweepInterrupted,
    checkpoint_exists,
)
from fantoch_tpu.engine.protocols import (
    dev_config_kwargs,
    dev_protocol,
    partial_dev_protocol,
)
from fantoch_tpu.parallel import aot
from fantoch_tpu.parallel.sweep import (
    LAST_STATS,
    default_scan_window,
    make_sweep_specs,
    run_sweep,
)
from fantoch_tpu.registry import DEV_PROTOCOLS, PARTIAL_DEV_PROTOCOLS

COMMANDS = 2
SEG = 8  # segments small enough that every lane spans several windows


def _blob(results) -> str:
    return json.dumps([r.to_json() for r in results], sort_keys=True)


def _specs(name: str, conflicts=(0, 100), subsets=4, shards=1):
    planet = Planet.new()
    regions = planet.regions()
    clients = 3
    pool = 1
    total = COMMANDS * clients
    if shards > 1:
        pool = 4
        dev = partial_dev_protocol(name, clients, shards, pool_size=pool)
        dims = EngineDims.for_partial(dev, 3, clients, total, regions=3)
        base = Config(
            **dev_config_kwargs(name, 3, 1),
            shard_count=shards,
            executor_executed_notification_interval_ms=100,
            executor_cleanup_interval_ms=100,
        )
    else:
        dev = dev_protocol(name, clients)
        dims = EngineDims.for_protocol(
            dev, n=3, clients=clients, payload=dev.payload_width(3),
            total_commands=total, dot_slots=total + 1, regions=3,
        )
        base = Config(**dev_config_kwargs(name, 3, 1))
    specs = make_sweep_specs(
        dev,
        planet,
        region_sets=[regions[i : i + 3] for i in range(subsets)],
        fs=[1],
        conflicts=list(conflicts),
        commands_per_client=COMMANDS,
        clients_per_region=1,
        dims=dims,
        config_base=base,
        pool_size=pool,
    )
    return dev, dims, specs


# ----------------------------------------------------------------------
# default-window resolution (host only)
# ----------------------------------------------------------------------


def test_default_scan_window_derives_from_segment_steps():
    # the documented 8192-step segment packs 4 segments per window...
    assert default_scan_window(8192) == 4
    # ...tiny debug segments clamp at the max...
    assert default_scan_window(8) == 8
    # ...and segments at/past the target run one per call
    assert default_scan_window(1 << 15) == 1
    assert default_scan_window(1 << 20) == 1


# ----------------------------------------------------------------------
# scan-fused ≡ segment loop (tier-1: tempo + basic)
# ----------------------------------------------------------------------


@pytest.mark.parametrize("name", ["basic", "tempo"])
def test_scan_fused_matches_segment_loop(name):
    dev, dims, specs = _specs(name)
    serial = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1,
        pipeline_depth=1,
    )
    serial_calls = LAST_STATS["device_calls"]
    ref = _blob(serial)
    assert serial[0].completed == COMMANDS * 3 and not serial[0].err
    assert serial_calls > 2, "lanes must span several segments"
    for win, depth in ((2, 1), (4, 2), (8, 2)):
        fused = run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=win,
            pipeline_depth=depth,
        )
        assert _blob(fused) == ref, f"scan_window={win} diverged"
        # host round-trips per sweep drop to ceil(segments/W) plus at
        # most depth−1 speculative windows — the window-granular
        # liveness bound (each speculative window is W fixed-point
        # no-op segments, so the early-exit overshoot is ≤ W segments
        # per in-flight slot, where the segment loop's was ≤ depth−1
        # SEGMENTS total)
        assert LAST_STATS["scan_window"] == win
        cap = math.ceil(serial_calls / win) + (depth - 1)
        assert LAST_STATS["device_calls"] <= cap, (
            win, depth, LAST_STATS["device_calls"], serial_calls,
        )
        assert LAST_STATS["segments_covered"] <= cap * win
    # the auto default composes the same way
    auto = run_sweep(dev, dims, specs, segment_steps=SEG)
    assert _blob(auto) == ref
    assert LAST_STATS["scan_window"] == default_scan_window(SEG)


# ----------------------------------------------------------------------
# checkpoints: window-size-free artifacts, ≤ one window lost
# ----------------------------------------------------------------------


def test_checkpoint_interchanges_across_scan_windows(tmp_path):
    dev, dims, specs = _specs("basic")
    control = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1,
        pipeline_depth=1,
    )
    ck = str(tmp_path / "ck")
    win = 2
    with pytest.raises(SweepInterrupted) as e:
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=win,
            pipeline_depth=2,
            checkpoint=CheckpointSpec(path=ck, stop_after_segments=1),
        )
    assert e.value.reason == "segment-limit"
    assert checkpoint_exists(ck)
    # a kill mid-window loses at most ONE window: the stop lands on
    # the first drained boundary past the request, i.e. exactly the
    # requested window count — never part-way into a later one
    assert e.value.until <= win * SEG, e.value.until
    # the window is a property of the executing loop, not of the work:
    # no scan_window meta key, exactly like pipeline_depth/mesh_shard
    manifest = json.load(open(os.path.join(ck, "manifest.json")))
    assert "scan_window" not in manifest["meta"]
    # resume under DIFFERENT window sizes — each from its own copy of
    # the artifact (a successful resume consumes it)
    for resume_win in (4, 1, None):
        ck2 = str(tmp_path / f"ck_{resume_win}")
        shutil.copytree(ck, ck2)
        resumed = run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=resume_win,
            checkpoint=CheckpointSpec(path=ck2),
        )
        assert not checkpoint_exists(ck2)
        assert _blob(resumed) == _blob(control), (
            f"resume under scan_window={resume_win} diverged"
        )


# ----------------------------------------------------------------------
# AOT executables: serialize → fresh-subprocess load → byte identity
# ----------------------------------------------------------------------

_AOT_CHILD = r"""
import json
import sys

from fantoch_tpu.parallel.sweep import LAST_STATS, run_sweep

sys.path.insert(0, {test_dir!r})
from test_scan_window import _blob, _specs

dev, dims, specs = _specs("basic")
results = run_sweep(
    dev, dims, specs, segment_steps=8, scan_window=4, aot={aot_dir!r}
)
assert LAST_STATS["aot"] is not None
assert LAST_STATS["aot"]["source"] == "aot-load", LAST_STATS["aot"]
print("AOT-CHILD " + json.dumps(
    {{"blob": _blob(results), "load_s": LAST_STATS["aot"]["seconds"]}}
))
"""


def _child_env():
    import fantoch_tpu

    repo = os.path.dirname(os.path.dirname(fantoch_tpu.__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("FANTOCH_SWEEP_DONATE", None)
    if "xla_force_host_platform_device_count" not in env.get(
        "XLA_FLAGS", ""
    ):
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    return env


def test_aot_roundtrip_fresh_subprocess_matches_traced(tmp_path):
    dev, dims, specs = _specs("basic")
    control = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1,
        pipeline_depth=1,
    )
    d = str(tmp_path / "aot")
    first = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=4, aot=d
    )
    assert LAST_STATS["aot"]["source"] == "trace-compile"
    assert _blob(first) == _blob(control)
    assert any(f.endswith(".bin") for f in os.listdir(d))
    # a fresh process finds the serialized executable and LOADS it —
    # no trace, no compile — and its results are byte-identical
    script = _AOT_CHILD.format(
        test_dir=os.path.dirname(os.path.abspath(__file__)),
        aot_dir=d,
    )
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=420, env=_child_env(),
    )
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    line = [
        ln for ln in out.stdout.splitlines() if ln.startswith("AOT-CHILD ")
    ][0]
    child = json.loads(line[len("AOT-CHILD "):])
    assert child["blob"] == _blob(control), "loaded executable diverged"


def test_aot_drift_and_corruption_refused_by_name(tmp_path):
    dev, dims, specs = _specs("basic")
    d = str(tmp_path / "aot")
    run_sweep(dev, dims, specs, segment_steps=SEG, scan_window=4, aot=d)
    manifests = sorted(
        f for f in os.listdir(d) if f.endswith(".json")
    )
    assert len(manifests) == 1
    mpath = os.path.join(d, manifests[0])
    pristine = open(mpath).read()

    # (a) code/toolchain drift: the manifest records a different step
    # jaxpr than this process traces — refused BY NAME, never
    # silently re-traced beside it
    doctored = json.loads(pristine)
    doctored["signature"]["step_jaxpr_sha256"] = "0" * 64
    with open(mpath, "w") as fh:
        json.dump(doctored, fh)
    with pytest.raises(aot.AotMismatchError, match="step_jaxpr"):
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=4, aot=d
        )
    with open(mpath, "w") as fh:
        fh.write(pristine)

    # (b) a corrupted payload fails its recorded sha256
    binf = [f for f in os.listdir(d) if f.endswith(".bin")][0]
    with open(os.path.join(d, binf), "r+b") as fh:
        fh.seek(16)
        fh.write(b"\xff\xff\xff\xff")
    with pytest.raises(aot.AotMismatchError, match="corrupt"):
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=4, aot=d
        )

    # (c) a DIFFERENT unit shape is not drift: it gets its own slot
    # and compiles fresh instead of refusing (campaign dirs hold one
    # executable per batch shape). 8 subsets = 16 padded lanes vs the
    # original 8 — a genuinely different compiled shape (2 subsets
    # would pad back to 8 on the 8-device mesh and correctly LOAD the
    # existing executable).
    dev2, dims2, specs2 = _specs("basic", conflicts=(0, 100), subsets=8)
    out = run_sweep(
        dev2, dims2, specs2, segment_steps=SEG, scan_window=4, aot=d
    )
    assert LAST_STATS["aot"]["source"] == "trace-compile"
    assert len(out) == len(specs2)
    assert len([f for f in os.listdir(d) if f.endswith(".bin")]) == 2


def test_aot_runner_is_undonated_on_pinned_jaxlib(tmp_path):
    """A donated deserialized executable reads freed buffers on this
    jaxlib (measured — see engine/core.py aot_donation_safe), so the
    AOT path must force donation off even where plain sweeps donate,
    and record that in the executable signature."""
    from fantoch_tpu.engine.core import aot_donation_safe

    if aot_donation_safe():
        pytest.skip("jaxlib pin moved past the donation fix")
    dev, dims, specs = _specs("basic", subsets=2)
    d = str(tmp_path / "aot")
    run_sweep(dev, dims, specs, segment_steps=SEG, scan_window=2, aot=d)
    manifest = json.load(
        open(os.path.join(d, sorted(
            f for f in os.listdir(d) if f.endswith(".json")
        )[0]))
    )
    assert manifest["signature"]["donate"] == "False"


# ----------------------------------------------------------------------
# the full matrix (slow tier: compiles)
# ----------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("name", DEV_PROTOCOLS)
def test_scan_fused_matches_segment_loop_full_protocols(name):
    dev, dims, specs = _specs(name, subsets=2)
    serial = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1,
        pipeline_depth=1,
    )
    for win in (2, 8):
        fused = run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=win
        )
        assert _blob(fused) == _blob(serial), (name, win)


@pytest.mark.slow
@pytest.mark.parametrize("name", PARTIAL_DEV_PROTOCOLS)
def test_scan_fused_matches_segment_loop_partial_twins(name):
    dev, dims, specs = _specs(name, conflicts=(50, 100), subsets=2,
                              shards=2)
    serial = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1,
        pipeline_depth=1,
    )
    for win in (2, 8):
        fused = run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=win
        )
        assert _blob(fused) == _blob(serial), (name, win)

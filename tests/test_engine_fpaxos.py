"""Device-engine FPaxos differential tests: latency means and GC totals
must match the host oracle runner on identical configurations (leader,
write quorum, slot-ordered execution)."""

from fantoch_tpu.client import ConflictPool, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.protocols import FPaxosDev
from fantoch_tpu.protocol import FPaxos
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

COMMANDS = 50
PROCESS_REGIONS = ["asia-east1", "us-central1", "us-west1"]
CLIENT_REGIONS = ["us-west1", "us-west2"]


def oracle(config):
    planet = Planet.new()
    workload = Workload(
        shard_count=1,
        key_gen=ConflictPool(conflict_rate=100, pool_size=1),
        keys_per_command=1,
        commands_per_client=COMMANDS,
        payload_size=0,
    )
    runner = Runner(
        FPaxos,
        planet,
        config,
        workload,
        1,
        PROCESS_REGIONS,
        list(CLIENT_REGIONS),
    )
    metrics, _, latencies = runner.run(extra_sim_time_ms=1000)
    stable = sum(
        pm.get_aggregated(ProtocolMetricsKind.STABLE) or 0
        for pm, _em in metrics.values()
    )
    return latencies, stable


def engine(config):
    planet = Planet.new()
    total = COMMANDS * len(CLIENT_REGIONS)
    dims = EngineDims.for_protocol(
        FPaxosDev,
        n=3,
        clients=2,
        payload=FPaxosDev.payload_width(3),
        total_commands=total,
        dot_slots=total + 1,
        regions=2,
    )
    spec = make_lane(
        FPaxosDev,
        planet,
        config,
        conflict_rate=100,
        pool_size=1,
        commands_per_client=COMMANDS,
        clients_per_region=1,
        process_regions=PROCESS_REGIONS,
        client_regions=CLIENT_REGIONS,
        dims=dims,
    )
    return run_lanes(FPaxosDev, dims, [spec])[0]


def test_engine_fpaxos_matches_oracle():
    for f, leader in [(1, 1), (1, 3), (2, 2)]:
        config = Config(n=3, f=f, leader=leader, gc_interval_ms=100)
        oracle_lat, oracle_stable = oracle(config)
        res = engine(config)
        assert not res.err, (f, leader)
        for region in CLIENT_REGIONS:
            _issued, hist = oracle_lat[region]
            assert res.latency_mean(region) == hist.mean(), (f, leader, region)
        # GC totals: stable slots counted at the f+1 acceptors only
        assert int(res.protocol_metrics["stable"].sum()) == oracle_stable, (
            f,
            leader,
        )

"""Bote latency model + search tests: the batched array path must agree
with the straightforward host model (which mirrors fantoch_bote), and
the ranked search must honor its own scoring rules."""

import itertools

import numpy as np

from fantoch_tpu.bote import (
    Bote,
    FTMetric,
    ProtocolModel,
    RankingParams,
    Search,
    batched_config_stats,
    compute_stats,
)
from fantoch_tpu.core import Planet


def test_quorum_sizes():
    """fantoch_bote/src/protocol.rs:118-135."""
    assert ProtocolModel.fpaxos(3, 1) == 2
    assert ProtocolModel.fpaxos(5, 2) == 3
    assert ProtocolModel.epaxos(3) == 2
    assert ProtocolModel.epaxos(5) == 3
    assert ProtocolModel.epaxos(7) == 5
    assert ProtocolModel.epaxos(11) == 8
    assert ProtocolModel.epaxos(17) == 12
    assert ProtocolModel.atlas(3, 1) == 2
    assert ProtocolModel.atlas(5, 1) == 3
    assert ProtocolModel.atlas(5, 2) == 4


def test_batched_matches_host_model():
    planet = Planet.new()
    bote = Bote(planet)
    regions = sorted(planet.regions())
    index = {r: i for i, r in enumerate(regions)}
    lat = planet.latency_matrix(regions).astype(np.float32)

    servers_sets = [
        ["asia-east1", "europe-west2", "us-central1"],
        ["asia-south1", "europe-north1", "southamerica-east1"],
        ["asia-east1", "asia-northeast1", "europe-west4", "us-east1",
         "us-west1"],
    ]
    clients = sorted(planet.regions())[:10]
    for servers in servers_sets:
        servers = sorted(servers)
        n = len(servers)
        q = ProtocolModel.atlas(n, 1)
        subsets = np.asarray([[index[r] for r in servers]])
        res = batched_config_stats(
            lat,
            subsets,
            np.asarray([index[c] for c in clients]),
            [q],
            leader_quorum_size=ProtocolModel.fpaxos(n, 1),
        )
        host = bote.leaderless(servers, clients, q)
        np.testing.assert_array_equal(
            res[f"lat_{q}"][0], [l for _c, l in host]
        )
        leader, hist = bote.best_leader(
            servers, clients, ProtocolModel.fpaxos(n, 1), sort_by="cov"
        )
        assert servers[int(res["leader"][0])] == leader
        np.testing.assert_allclose(
            float(np.mean(res["leader_lat"][0])), hist.mean(), rtol=1e-6
        )


def test_search_ranks_and_scores():
    planet = Planet.new()
    servers = sorted(planet.regions())[:8]
    search = Search(planet, servers=servers, clients=servers)
    params = RankingParams(
        min_mean_fpaxos_improv=-1000.0,
        min_fairness_fpaxos_improv=-1000.0,
        min_n=3,
        max_n=5,
        ft_metric=FTMetric.F1,
    )
    ranked = search.rank(params)
    assert set(ranked) == {3, 5}
    for n, configs in ranked.items():
        assert len(configs) == len(
            list(itertools.combinations(servers, n))
        )
        scores = [rc.score for rc in configs]
        assert scores == sorted(scores, reverse=True)

    # cross-check the top n=3 config's score against the host model
    bote = Bote(planet)
    top = ranked[3][0]
    stats = compute_stats(list(top.config), servers, bote)
    expected = (stats["ff1"].mean() - stats["af1"].mean()) + 30.0 * (
        stats["e"].mean() - stats["af1"].mean()
    )
    assert abs(top.score - expected) < 1e-3


def test_ranked_means_match_host_stats():
    """Every ranked config's means must equal the host compute_stats
    (Histogram-of-integers) means exactly — the search reduces the
    device latencies in f64 (search.rs ranks from Histogram stats)."""
    planet = Planet.new()
    servers = sorted(planet.regions())[:6]
    search = Search(planet, servers=servers, clients=servers)
    params = RankingParams(
        min_mean_fpaxos_improv=-1000.0,
        min_fairness_fpaxos_improv=-1000.0,
        min_n=3,
        max_n=3,
        ft_metric=FTMetric.F1,
    )
    ranked = search.rank(params)[3]
    bote = Bote(planet)
    for rc in ranked:
        stats = compute_stats(list(rc.config), servers, bote)
        assert rc.means["af1"] == stats["af1"].mean()
        assert rc.means["ff1"] == stats["ff1"].mean()
        assert rc.means["e"] == stats["e"].mean()


def test_tighter_params_filter_configs():
    planet = Planet.new()
    servers = sorted(planet.regions())[:8]
    search = Search(planet, servers=servers, clients=servers)
    strict = RankingParams(
        min_mean_fpaxos_improv=30.0,
        min_fairness_fpaxos_improv=0.0,
        min_n=3,
        max_n=3,
        ft_metric=FTMetric.F1,
    )
    lenient = RankingParams(
        min_mean_fpaxos_improv=-1000.0,
        min_fairness_fpaxos_improv=-1000.0,
        min_n=3,
        max_n=3,
        ft_metric=FTMetric.F1,
    )
    assert len(search.rank(strict)[3]) < len(search.rank(lenient)[3])

"""Device-engine Caesar differential tests.

Same bar as the other device protocols: on tie-free schedules the array
engine reproduces the host oracle exactly — per-region latency means,
fast/slow-path counts, GC stable totals. The reference asserts no
particular fast/slow split for Caesar (the wait condition makes it
timing-dependent, see test_sim_caesar.py), so the concurrent variants
assert the harness invariants instead.
"""

import pytest

from fantoch_tpu.client import ConflictPool, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.protocols import CaesarDev
from fantoch_tpu.protocol import Caesar
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.sim import Runner


def run_oracle(config, regions, conflict, commands, cpr):
    planet = Planet.new()
    workload = Workload(
        shard_count=1,
        key_gen=ConflictPool(conflict_rate=conflict, pool_size=1),
        keys_per_command=1,
        commands_per_client=commands,
        payload_size=0,
    )
    runner = Runner(
        Caesar, planet, config, workload, cpr, regions, list(regions)
    )
    metrics, _, latencies = runner.run(extra_sim_time_ms=1000)
    fast = slow = stable = 0
    for pm, _em in metrics.values():
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
        stable += pm.get_aggregated(ProtocolMetricsKind.STABLE) or 0
    return latencies, fast, slow, stable


def run_engine(config, regions, conflict, commands, cpr):
    planet = Planet.new()
    clients = cpr * len(regions)
    dev = CaesarDev.for_load(keys=1 + clients, clients=clients)
    total = commands * clients
    dims = EngineDims.for_protocol(
        dev,
        n=config.n,
        clients=clients,
        payload=dev.payload_width(config.n),
        total_commands=total,
        dot_slots=total + 1,
        regions=len(regions),
    )
    spec = make_lane(
        dev,
        planet,
        config,
        conflict_rate=conflict,
        pool_size=1,
        commands_per_client=commands,
        clients_per_region=cpr,
        process_regions=regions,
        client_regions=regions,
        dims=dims,
    )
    return run_lanes(dev, dims, [spec])[0]


@pytest.mark.parametrize(
    "n,f,wait,conflict,commands,cpr",
    [
        (3, 1, True, 100, 30, 1),
        (3, 1, False, 100, 30, 1),
        (3, 1, True, 0, 30, 2),
        (5, 2, True, 100, 10, 1),
        (5, 2, False, 100, 10, 1),
        # reference sim_test scale (mod.rs:639-705: 100 commands)
        pytest.param(3, 1, True, 100, 100, 1, marks=pytest.mark.slow),
        pytest.param(5, 2, True, 100, 100, 1, marks=pytest.mark.slow),
    ],
)
def test_engine_caesar_matches_oracle_exactly(
    n, f, wait, conflict, commands, cpr
):
    """Tie-free schedules: every metric matches the oracle exactly."""
    config = Config(
        n=n, f=f, gc_interval_ms=100, caesar_wait_condition=wait
    )
    regions = Planet.new().regions()[:n]
    oracle_lat, fast, slow, stable = run_oracle(
        config, regions, conflict, commands, cpr
    )
    res = run_engine(config, regions, conflict, commands, cpr)
    assert not res.err
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    assert int(res.protocol_metrics["stable"].sum()) == stable
    for region in regions:
        _issued, hist = oracle_lat[region]
        assert res.latency_mean(region) == hist.mean(), region


@pytest.mark.slow
def test_engine_caesar_concurrent_invariants():
    """Same-instant concurrency: tie orders may differ; assert protocol
    invariants and closeness of latency means."""
    n, f, conflict, commands, cpr = 5, 2, 100, 20, 2
    config = Config(
        n=n, f=f, gc_interval_ms=100, caesar_wait_condition=True
    )
    regions = Planet.new().regions()[:n]
    oracle_lat, fast, slow, stable = run_oracle(
        config, regions, conflict, commands, cpr
    )
    res = run_engine(config, regions, conflict, commands, cpr)
    assert not res.err
    total_commits = commands * cpr * n
    dev_fast = int(res.protocol_metrics["fast_path"].sum())
    dev_slow = int(res.protocol_metrics["slow_path"].sum())
    assert dev_fast + dev_slow == total_commits == fast + slow
    assert int(res.protocol_metrics["stable"].sum()) == n * total_commits
    for region in regions:
        _issued, hist = oracle_lat[region]
        assert res.issued(region) == commands * cpr
        assert abs(res.latency_mean(region) - hist.mean()) <= 0.1 * hist.mean()

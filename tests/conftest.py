"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware. The environment presets
JAX_PLATFORMS=axon (a tunneled TPU) *and* pre-imports jax at interpreter
startup, so plain env-var overrides are too late — but XLA backends
initialize lazily, so flipping the config before the first computation
still works. Benches target real hardware; tests target the
deterministic CPU mesh.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax has no such option; the XLA_FLAGS export above already
    # provides the 8-device host platform as long as jax was imported
    # fresh in this process
    pass

# the persistent XLA compile cache turns every re-run of the engine
# tests from minutes of XLA work into a disk read (same cache the
# bench/CLI/tools share — fantoch_tpu.platform.enable_compile_cache)
from fantoch_tpu.platform import enable_compile_cache  # noqa: E402

enable_compile_cache()

import subprocess  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402


# every process this pytest run spawns (directly or through the exp
# layer's local/fake-ssh transports) inherits this marker in its
# environment — the precise ownership test for the leak check, immune
# to both reparenting (an orphan keeps its environ) and concurrent
# sessions on the machine (theirs carry a different id or none)
_RUN_MARKER = f"FANTOCH_TEST_RUN_ID={os.getpid()}-{int(time.time())}"
os.environ[_RUN_MARKER.split("=")[0]] = _RUN_MARKER.split("=")[1]


def _ours(pid: int) -> bool:
    try:
        with open(f"/proc/{pid}/environ", "rb") as fh:
            return _RUN_MARKER.encode() in fh.read().replace(b"\0", b"\n")
    except OSError:
        return False


def _server_pids() -> set:
    """PIDs of live ``fantoch_tpu proc`` servers spawned by THIS pytest
    run (the bracket keeps the pattern from matching pgrep's own
    command line; the environ marker keeps it blind to other
    sessions)."""
    out = subprocess.run(
        ["pgrep", "-f", "[f]antoch_tpu proc"], capture_output=True,
        text=True,
    ).stdout
    return {int(p) for p in out.split() if _ours(int(p))}


@pytest.fixture(autouse=True)
def no_leaked_servers():
    """Round-4 judging found orphaned 3-replica clusters (hours old,
    reparented to init) left behind by PASSING exp-layer tests: for an
    SSH testbed the teardown killed only the local ssh client. Every
    test now asserts it leaked no server process; pre-existing pids
    (e.g. a concurrent session's own experiment) are excluded."""
    before = _server_pids()
    yield
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        leaked = _server_pids() - before
        if not leaked:
            return
        time.sleep(0.25)
    raise AssertionError(
        f"test leaked fantoch_tpu server processes: {sorted(leaked)}"
    )

"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware. The environment presets
JAX_PLATFORMS=axon (a tunneled TPU) *and* pre-imports jax at interpreter
startup, so plain env-var overrides are too late — but XLA backends
initialize lazily, so flipping the config before the first computation
still works. Benches target real hardware; tests target the
deterministic CPU mesh.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import subprocess  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402


def _is_descendant(pid: int, ancestor: int) -> bool:
    """Walk /proc ppid links; True when ``ancestor`` is on the chain.
    Keeps the leak check blind to servers another session on this
    machine is legitimately running during our test window."""
    for _ in range(64):
        if pid == ancestor:
            return True
        try:
            with open(f"/proc/{pid}/stat") as fh:
                pid = int(fh.read().rsplit(")", 1)[1].split()[1])
        except (OSError, ValueError, IndexError):
            return False
        if pid <= 1:
            # reparented to init: its real parent is gone — that is
            # exactly what a leak looks like, so attribute it to us
            return True
    return False


def _server_pids() -> set:
    """PIDs of live ``fantoch_tpu proc`` server processes descended
    from this pytest run (the bracket keeps the pattern from matching
    pgrep's own command line)."""
    out = subprocess.run(
        ["pgrep", "-f", "[f]antoch_tpu proc"], capture_output=True,
        text=True,
    ).stdout
    me = os.getpid()
    return {
        int(p) for p in out.split() if _is_descendant(int(p), me)
    }


@pytest.fixture(autouse=True)
def no_leaked_servers():
    """Round-4 judging found orphaned 3-replica clusters (hours old,
    reparented to init) left behind by PASSING exp-layer tests: for an
    SSH testbed the teardown killed only the local ssh client. Every
    test now asserts it leaked no server process; pre-existing pids
    (e.g. a concurrent session's own experiment) are excluded."""
    before = _server_pids()
    yield
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        leaked = _server_pids() - before
        if not leaked:
            return
        time.sleep(0.25)
    raise AssertionError(
        f"test leaked fantoch_tpu server processes: {sorted(leaked)}"
    )

"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware. The environment presets
JAX_PLATFORMS=axon (a tunneled TPU) *and* pre-imports jax at interpreter
startup, so plain env-var overrides are too late — but XLA backends
initialize lazily, so flipping the config before the first computation
still works. Benches target real hardware; tests target the
deterministic CPU mesh.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

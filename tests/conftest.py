"""Test configuration.

Device-engine tests run on a virtual 8-device CPU mesh so multi-chip
sharding is exercised without TPU hardware; this must be set before jax is
imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

"""Shutdown robustness: SIGTERM must terminate a server in EVERY state.

Round-4 judging found (a) a cohort that ignored SIGTERM for >10 minutes
when every replica was signalled simultaneously and needed SIGKILL, and
(b) a bootstrap that never raced ``stop_event`` — a server stuck
connecting to peers that will never come up could not be stopped
gracefully. These tests pin both fixes: the bootstrap race in
``_Runtime.run`` and the grace-period watchdog in ``cmd_proc``
(the reference relies on the remote ``kill`` doing its job,
fantoch_exp/src/bench.rs:596-634; our processes must honor it).
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time

from fantoch_tpu.exp.bench import _free_ports, _wait_markers

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_REPO)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _proc_argv(pid, n, port_of, cport_of, extra=()):
    addresses = ",".join(
        f"{q}=127.0.0.1:{p}" for q, p in port_of.items() if q != pid
    )
    sorted_ps = ",".join(
        [f"{pid}:0"] + [f"{q}:0" for q in port_of if q != pid]
    )
    return [
        sys.executable, "-m", "fantoch_tpu", "proc",
        "--protocol", "tempo", "--id", str(pid), "--n", str(n),
        "--f", "1", "--port", str(port_of[pid]),
        "--client-port", str(cport_of[pid]),
        "--addresses", addresses, "--sorted", sorted_ps,
        *extra,
    ]


def test_sigterm_during_bootstrap():
    """A server stuck in its peer-connect loop (peers never come up)
    must exit promptly on SIGTERM — stop_event aborts the bootstrap,
    not the 100 s retry budget and not the force-exit watchdog (the
    grace is set far above the asserted exit bound to prove it)."""
    ports = _free_ports(6)
    port_of = {1: ports[0], 2: ports[2], 3: ports[4]}
    cport_of = {1: ports[1], 2: ports[3], 3: ports[5]}
    # peer 2 accepts (observably: the test sees the connection, which
    # means the server is past imports and inside _connect_to_all);
    # peer 3 stays unreachable, parking the bootstrap in its retry loop
    gate = socket.socket()
    gate.bind(("127.0.0.1", port_of[2]))
    gate.listen(4)
    gate.settimeout(30)
    proc = subprocess.Popen(
        _proc_argv(1, 3, port_of, cport_of,
                   extra=("--connect-retries", "2000")),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_env(FANTOCH_SHUTDOWN_GRACE_S=60),
    )
    try:
        conn, _ = gate.accept()  # server reached the connect phase
        time.sleep(0.2)
        t0 = time.monotonic()
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=10)
        elapsed = time.monotonic() - t0
        assert elapsed < 5, f"bootstrap ignored SIGTERM for {elapsed:.1f}s"
        assert rc == 0, proc.stdout.read()
        conn.close()
    finally:
        gate.close()
        if proc.poll() is None:
            proc.kill()


def test_sigterm_all_replicas_simultaneously():
    """Signalling every replica of a healthy cluster at the same time
    must terminate all of them — the exact scenario whose leaked cohort
    needed SIGKILL during round-4 judging. The watchdog grace bounds
    even a wedged graceful path."""
    ports = _free_ports(6)
    port_of = {1: ports[0], 2: ports[2], 3: ports[4]}
    cport_of = {1: ports[1], 2: ports[3], 3: ports[5]}
    procs = [
        subprocess.Popen(
            _proc_argv(pid, 3, port_of, cport_of),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_env(FANTOCH_SHUTDOWN_GRACE_S=8),
        )
        for pid in (1, 2, 3)
    ]
    try:
        _wait_markers(
            procs,
            [f"process {pid} started" for pid in (1, 2, 3)],
            time.monotonic() + 30,
        )
        for p in procs:
            p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 15  # grace 8 s + margin
        for p in procs:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
    finally:
        survivors = [p for p in procs if p.poll() is None]
        for p in survivors:  # kill ALL strays before failing the test
            p.kill()
        if survivors:
            raise AssertionError(
                f"{len(survivors)} replica(s) survived simultaneous "
                "SIGTERM past the watchdog grace"
            )

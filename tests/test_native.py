"""Native atomic key-clock sequencer tests.

Mirrors the reference's coverage of ``AtomicKeyClocks``: single-threaded
semantic equivalence with the sequential variant (clocks/keys/mod.rs
tests run every KeyClocks impl through the same assertions) and the
multi-threaded gap-free-votes stress test (clocks/keys/mod.rs:70-338).
"""

import pytest

from fantoch_tpu.native import AtomicKeyClocks, available

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable"
)


def merge_votes(votes):
    """(key, start, end) triples -> key -> sorted set of voted values."""
    out = {}
    for key, start, end in votes:
        out.setdefault(key, set()).update(range(start, end + 1))
    return out


def test_proposal_single_key():
    kc = AtomicKeyClocks(16)
    clock, votes = kc.proposal([3])
    assert clock == 1 and votes == [(3, 1, 1)]
    clock, votes = kc.proposal([3])
    assert clock == 2 and votes == [(3, 2, 2)]
    # min_clock floor vacates the whole range
    clock, votes = kc.proposal([3], min_clock=10)
    assert clock == 10 and votes == [(3, 3, 10)]
    assert kc.clock(3) == 10


def test_proposal_two_round_equalizes():
    """The two-round bump leaves every key of the command at the
    proposal clock, with the vacated ranges split across rounds
    (atomic.rs:28-63)."""
    kc = AtomicKeyClocks(16)
    kc.proposal([1], min_clock=5)  # key 1 at clock 5
    clock, votes = kc.proposal([1, 2])
    assert clock == 6
    merged = merge_votes(votes)
    # key 1: vacated 6; key 2: round 1 gave 1, round 2 lifted to 6
    assert merged[1] == {6}
    assert merged[2] == {1, 2, 3, 4, 5, 6}
    assert kc.clock(1) == kc.clock(2) == 6


def test_detached():
    kc = AtomicKeyClocks(16)
    kc.proposal([7])
    votes = kc.detached([7, 8], up_to=4)
    merged = merge_votes(votes)
    assert merged[7] == {2, 3, 4}
    assert merged[8] == {1, 2, 3, 4}
    # already past: no votes
    assert kc.detached([7], up_to=2) == []


def test_matches_sequential_semantics():
    """Single-threaded, the atomic sequencer's (clock, votes) stream is
    the sequential variant's: proposal bumps every key to
    max(min_clock, per-key max + 1) and vacates exactly the skipped
    ranges (sequential.rs:36-104)."""
    kc = AtomicKeyClocks(64)
    shadow = {}  # key -> clock

    def seq_proposal(keys, min_clock):
        clock = max([min_clock] + [shadow.get(k, 0) + 1 for k in keys])
        votes = {}
        for k in keys:
            cur = shadow.get(k, 0)
            if cur < clock:
                votes[k] = set(range(cur + 1, clock + 1))
                shadow[k] = clock
        return clock, votes

    import random

    rng = random.Random(42)
    for _ in range(500):
        keys = rng.sample(range(20), rng.choice([1, 2, 3]))
        floor = rng.choice([0, 0, 0, rng.randrange(1, 40)])
        got_clock, got_votes = kc.proposal(keys, floor)
        want_clock, want_votes = seq_proposal(keys, floor)
        # the atomic round-1 bump of a later key can exceed an earlier
        # key's bump only under concurrency; single-threaded the final
        # clock and the merged votes must match exactly
        assert got_clock == want_clock
        assert merge_votes(got_votes) == want_votes


@pytest.mark.parametrize("threads", [2, 4, 8])
def test_stress_gap_free_votes(threads):
    """The reference's race-detection strategy for the sequencer: reap
    all votes from all threads and assert they exactly cover
    1..=final_clock per key (table/clocks/keys/mod.rs:70-338)."""
    kc = AtomicKeyClocks(100)
    ok, _secs = kc.stress(
        threads, ops_per_thread=2000, key_count=100, keys_per_op=2
    )
    assert ok, "votes not gap-free/duplicate-free"


def test_tempo_atomic_matches_sequential_sim():
    """TempoAtomic (native AtomicKeyClocks, the tempo_atomic binary's
    variant) behaves byte-identically to sequential Tempo in the
    deterministic sim — same slow-path count, monitors checked by the
    harness invariants."""
    from harness import sim_test

    from fantoch_tpu.core import Config
    from fantoch_tpu.protocol import Tempo, TempoAtomic

    config = Config(n=3, f=1, tempo_detached_send_interval_ms=100)
    kw = dict(commands_per_client=10, clients_per_process=2)
    assert sim_test(TempoAtomic, config, **kw) == sim_test(
        Tempo, config, **kw
    )

"""Whole-protocol simulation tests for the Basic protocol.

Mirrors fantoch/src/sim/runner.rs:723-871: the deterministic latency means
for Basic n=3 over the GCP planet are exact regression targets, including
the GC completeness assertion (all commands stable at every process).
"""

import pytest

from fantoch_tpu.client import ConflictPool, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.protocol import Basic
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

COMMANDS_PER_CLIENT = 1000


def run(f, clients_per_process, commands_per_client=COMMANDS_PER_CLIENT):
    planet = Planet.new()
    config = Config(n=3, f=f, gc_interval_ms=100)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictPool(conflict_rate=100, pool_size=1),
        keys_per_command=1,
        commands_per_client=commands_per_client,
        payload_size=100,
    )
    runner = Runner(
        Basic,
        planet,
        config,
        workload,
        clients_per_process,
        ["asia-east1", "us-central1", "us-west1"],
        ["us-west1", "us-west2"],
    )
    metrics, _monitors, latencies = runner.run(extra_sim_time_ms=1000)

    expected = commands_per_client * clients_per_process
    issued1, us_west1 = latencies["us-west1"]
    issued2, us_west2 = latencies["us-west2"]
    assert issued1 == expected
    assert issued2 == expected

    # all commands must have been garbage collected at every process
    for _pid, (process_metrics, _executor_metrics) in metrics.items():
        stable = process_metrics.get_aggregated(ProtocolMetricsKind.STABLE)
        assert stable == expected * 2
    return us_west1, us_west2


@pytest.mark.parametrize(
    "f,mean1,mean2", [(0, 0.0, 24.0), (1, 34.0, 58.0), (2, 118.0, 142.0)]
)
def test_runner_single_client_per_process(f, mean1, mean2):
    us_west1, us_west2 = run(f, clients_per_process=1)
    assert us_west1.mean() == mean1
    assert us_west2.mean() == mean2


def test_runner_multiple_clients_per_process():
    one = run(1, clients_per_process=1, commands_per_client=200)
    ten = run(1, clients_per_process=10, commands_per_client=200)
    # latency stats are independent of the client count (runner.rs:851-870)
    assert one[0].mean() == ten[0].mean()
    assert one[0].cov() == ten[0].cov()
    assert one[1].mean() == ten[1].mean()
    assert one[1].cov() == ten[1].cov()

"""Per-lane error taxonomy: a failing lane names its cause.

Round-1 VERDICT weak #8: with one opaque ``err`` bool, a 10k-lane sweep
failure was undebuggable. The engine and every device protocol now OR
``dims.ERR_*`` bits into int32 error words; these tests force each
engine-level failure mode on purpose and assert the decoded cause.
"""

import numpy as np

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.dims import (
    ERR_DOT,
    ERR_POOL,
    ERR_TRUNCATED,
    err_names,
)
from fantoch_tpu.engine.protocols import BasicDev

PROCESS_REGIONS = ["asia-east1", "us-central1", "us-west1"]
CLIENT_REGIONS = ["us-west1", "us-west2"]


def run_with(dims, commands=20, max_steps=1 << 22):
    planet = Planet.new()
    spec = make_lane(
        BasicDev,
        planet,
        Config(n=3, f=1, gc_interval_ms=100),
        conflict_rate=100,
        pool_size=1,
        commands_per_client=commands,
        clients_per_region=1,
        process_regions=PROCESS_REGIONS,
        client_regions=CLIENT_REGIONS,
        dims=dims,
        extra_time_ms=1000,
    )
    return run_lanes(BasicDev, dims, [spec], max_steps=max_steps)[0]


def base_dims(**over):
    kw = dict(
        n=3,
        clients=2,
        payload=BasicDev.payload_width(3),
        total_commands=40,
        dot_slots=41,
        regions=len(CLIENT_REGIONS),
    )
    kw.update(over)
    return EngineDims.for_protocol(BasicDev, **kw)


def test_clean_run_reports_ok():
    res = run_with(base_dims())
    assert res.err == 0
    assert res.err_cause == "ok"
    assert res.pool_peak > 0


def test_pool_overflow_named():
    res = run_with(base_dims(pool=4, total_commands=None))
    assert res.err & ERR_POOL
    assert "pool-overflow" in res.err_cause
    assert res.completed < 40  # the lane stopped early, not silently

def test_tiny_dot_window_backpressures():
    """A 2-slot dot window no longer kills the lane: the readiness gate
    requeues MStores whose slot awaits GC, so the lane completes under
    backpressure (slower — more steps — but correct)."""
    ref = run_with(base_dims())
    res = run_with(base_dims(dot_slots=2))
    assert res.err == 0, res.err_cause
    assert res.completed == 40
    assert res.steps > ref.steps  # requeue spin is visible, not free
    assert res.requeues > 0 and ref.requeues == 0  # stalls are loud


def test_truncation_named():
    res = run_with(base_dims(), max_steps=16)
    assert res.err & ERR_TRUNCATED
    assert "truncated" in res.err_cause


def test_err_names_decodes_unions():
    assert err_names(0) == "ok"
    assert err_names(ERR_POOL | ERR_DOT) == "pool-overflow+dot-collision"

"""Run-layer tests: the full TCP stack inside one process.

The analog of the reference's ``run_test`` (fantoch/src/run/mod.rs:
575-849 boots n [× shard_count] real processes on random localhost
ports plus real clients; fantoch_ps/src/protocol/mod.rs:579-637 wraps
it per protocol): every replica and client here runs over real asyncio
TCP connections with artificial per-connection delays, and the checks
are the same — identical per-key execution order on every replica,
complete GC, sane fast/slow-path counts.
"""

from __future__ import annotations

import asyncio
import socket

import pytest

from fantoch_tpu.client import ConflictPool, Workload
from fantoch_tpu.core import Config
from fantoch_tpu.core.ids import process_ids
from fantoch_tpu.protocol import Atlas, Basic, Caesar, EPaxos, FPaxos, Tempo
from fantoch_tpu.run import client as run_client
from fantoch_tpu.run import process as run_process

from harness import check_metrics, check_monitors, extract_process_metrics

COMMANDS = 10
CLIENTS_PER_PROCESS = 2


def _bind() -> socket.socket:
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    return s


async def _boot_cluster(protocol_cls, config, delay_ms=1, workers=1,
                        executors=1, multiplexing=1):
    """Start config.n × config.shard_count replicas on pre-bound
    localhost ports; returns (handles, client_addresses)."""
    ids = [
        (pid, shard)
        for shard in range(config.shard_count)
        for pid in process_ids(shard, config.n)
    ]
    peer_socks = {pid: _bind() for pid, _ in ids}
    client_socks = {pid: _bind() for pid, _ in ids}
    peer_addr = {
        pid: ("127.0.0.1", sock.getsockname()[1])
        for pid, sock in peer_socks.items()
    }
    client_addr = {
        pid: ("127.0.0.1", sock.getsockname()[1])
        for pid, sock in client_socks.items()
    }
    shards = dict(ids)
    handles = []
    for pid, shard in ids:
        # same-shard processes in id order, plus the co-located (same
        # region index) process of every other shard — discovery expects
        # exactly one closest process per remote shard (base.rs:57-131)
        mine = process_ids(shard, config.n)
        idx = mine.index(pid)
        sorted_ps = [(pid, shard)] + [
            (q, shard) for q in mine if q != pid
        ] + [
            (process_ids(s, config.n)[idx], s)
            for s in range(config.shard_count)
            if s != shard
        ]
        handles.append(
            await run_process(
                protocol_cls,
                pid,
                shard,
                config,
                peer_addresses={
                    q: peer_addr[q] for q, _ in ids if q != pid
                },
                peer_shards={q: s for q, s in ids if q != pid},
                peer_sock=peer_socks[pid],
                client_sock=client_socks[pid],
                sorted_processes=sorted_ps,
                delay_ms=delay_ms,
                workers=workers,
                executors=executors,
                multiplexing=multiplexing,
            )
        )
    await asyncio.gather(*(h.started.wait() for h in handles))
    return handles, client_addr, shards


def _merged_monitor(handle):
    """Merge a handle's per-executor monitors into one (pool members own
    disjoint key sets: key-hash for table pools, everything-on-0 for
    graph pools), so cross-replica order checks see whole processes."""
    from fantoch_tpu.core.kvs import ExecutionOrderMonitor

    merged = ExecutionOrderMonitor()
    for m in handle.monitors():
        for k in m.keys():
            assert k not in merged.order, f"key {k!r} on two pool members"
            merged.order[k] = list(m.get_order(k))
    return merged


async def _run_cluster(protocol_cls, config, keys_per_command=2,
                       workers=1, executors=1):
    config = config.with_(
        executor_monitor_execution_order=True,
        gc_interval_ms=25,
        executor_executed_notification_interval_ms=25,
        executor_cleanup_interval_ms=5,
    )
    handles, client_addr, shards = await _boot_cluster(
        protocol_cls, config, workers=workers, executors=executors
    )
    workload = Workload(
        shard_count=config.shard_count,
        key_gen=ConflictPool(conflict_rate=50, pool_size=1),
        keys_per_command=keys_per_command,
        commands_per_client=COMMANDS,
        payload_size=1,
    )
    # one client group per shard-0 process; multi-shard groups connect
    # to the same region's process of every shard
    groups = []
    shard0 = [h for h in handles if h.shard_id == 0]
    for i, h in enumerate(shard0):
        cids = [
            1 + i * CLIENTS_PER_PROCESS + j
            for j in range(CLIENTS_PER_PROCESS)
        ]
        shard_processes = {0: h.process_id}
        for shard in range(1, config.shard_count):
            peer = process_ids(shard, config.n)[i]
            shard_processes[shard] = peer
        groups.append(
            run_client(
                cids,
                {s: client_addr[p] for s, p in shard_processes.items()},
                shard_processes,
                workload,
            )
        )
    results = await asyncio.gather(*groups)
    total = COMMANDS * CLIENTS_PER_PROCESS * len(shard0)
    for r in results:
        assert all(
            len(d.latency_data()) == COMMANDS for d in r.data.values()
        )

    # wait for GC to complete everywhere (the sim harness's
    # extra_sim_time analog, bounded instead of fixed)
    # each command is GC'd at the n processes of its dot's shard
    # (test_sim_partial.py's `stable == n * total_cmds`); FPaxos GCs at
    # the f+1 acceptors
    expected = (config.f + 1 if protocol_cls is FPaxos else config.n) * total
    for _ in range(100):
        stable = sum(
            extract_process_metrics(h.metrics())[2] for h in handles
        )
        if stable >= expected:
            break
        await asyncio.sleep(0.05)

    per_process = {
        h.process_id: extract_process_metrics(h.metrics())
        for h in handles
        if h.shard_id == 0
    }
    monitors = {}
    for h in handles:
        assert len(h.monitors()) == executors
        monitors[(h.shard_id, h.process_id)] = _merged_monitor(h)
    for h in handles:
        await h.stop()

    # per-shard execution-order equality (each shard owns its keys);
    # Basic is the toy protocol and promises no such thing (the
    # reference's sim/run tests only check it for the real protocols)
    if protocol_cls is not Basic:
        for shard in range(config.shard_count):
            check_monitors(
                {
                    pid: m
                    for (s, pid), m in monitors.items()
                    if s == shard
                }
            )
    if config.shard_count == 1 and protocol_cls is not Basic:
        check_metrics(
            config, COMMANDS, CLIENTS_PER_PROCESS, per_process
        )
    else:
        # Basic / multi-shard: GC completeness only (Basic commits are
        # not fast/slow-path classified)
        stable = sum(
            extract_process_metrics(h.metrics())[2] for h in handles
        )
        assert stable >= expected, f"incomplete GC: {stable} < {expected}"


def _run(protocol_cls, config, **kw):
    asyncio.run(_run_cluster(protocol_cls, config, **kw))


def test_run_basic():
    _run(Basic, Config(n=3, f=1))


def test_run_fpaxos():
    _run(FPaxos, Config(n=3, f=1, leader=1))


def test_run_tempo():
    _run(Tempo, Config(n=3, f=1, tempo_detached_send_interval_ms=25))


def test_run_atlas():
    _run(Atlas, Config(n=3, f=1))


def test_run_epaxos():
    _run(EPaxos, Config(n=3, f=1))


def test_run_caesar():
    _run(Caesar, Config(n=3, f=1, caesar_wait_condition=True))


def test_run_tempo_partial_replication():
    _run(
        Tempo,
        Config(n=3, f=1, shard_count=2, tempo_detached_send_interval_ms=25),
    )


def test_run_atlas_partial_replication():
    _run(Atlas, Config(n=3, f=1, shard_count=2))


def test_run_basic_executor_pool():
    """A 2-wide key-hash executor pool (task/server/executor.rs:
    MessageKey routing) on the Basic protocol: keys split across the
    pool, every command completes, and the per-process execution counts
    add up across executors."""

    async def main():
        config = Config(
            n=3, f=1,
            executor_monitor_execution_order=True,
            gc_interval_ms=25,
            executor_executed_notification_interval_ms=25,
        )
        ids = [(pid, 0) for pid in process_ids(0, config.n)]
        peer_socks = {pid: _bind() for pid, _ in ids}
        client_socks = {pid: _bind() for pid, _ in ids}
        paddr = {
            p: ("127.0.0.1", s.getsockname()[1])
            for p, s in peer_socks.items()
        }
        caddr = {
            p: ("127.0.0.1", s.getsockname()[1])
            for p, s in client_socks.items()
        }
        handles = []
        for pid, shard in ids:
            handles.append(await run_process(
                Basic, pid, shard, config,
                peer_addresses={q: paddr[q] for q, _ in ids if q != pid},
                peer_shards={q: s for q, s in ids if q != pid},
                peer_sock=peer_socks[pid], client_sock=client_socks[pid],
                sorted_processes=[(pid, shard)]
                + [(q, s) for q, s in ids if q != pid],
                executors=2,
            ))
        for h in handles:
            await h.started.wait()
        workload = Workload(
            shard_count=1,
            key_gen=ConflictPool(conflict_rate=50, pool_size=4),
            keys_per_command=2, commands_per_client=COMMANDS,
            payload_size=1,
        )
        res = await run_client([1, 2], {0: caddr[1]}, {0: 1}, workload)
        assert all(
            len(d.latency_data()) == COMMANDS for d in res.data.values()
        )
        # commits reach the non-coordinator replicas after the client
        # already has its result: poll, don't sleep
        def totals():
            return [
                sum(
                    len(m.get_order(k))
                    for m in h.monitors()
                    for k in m.keys()
                )
                for h in handles
            ]

        # every process executes each command once per key
        expect = 2 * COMMANDS * 2
        for _ in range(100):
            if all(t == expect for t in totals()):
                break
            await asyncio.sleep(0.05)
        assert all(t == expect for t in totals()), totals()
        for h in handles:
            monitors = h.monitors()
            assert len(monitors) == 2, "expected one monitor per executor"
            keys0 = set(monitors[0].keys())
            keys1 = set(monitors[1].keys())
            assert keys0.isdisjoint(keys1), "executors must split keys"
            assert keys0 and keys1, (
                "both executors should own keys with a 4-key pool"
            )
        for h in handles:
            await h.stop()

    asyncio.run(main())


def test_run_client_batching():
    """Client-side batching (batcher.rs:15-100): four concurrent
    closed-loop clients sharing a connection merge commands up to
    batch_max_size, so the wire carries strictly fewer submits than
    commands, while every member rifl still completes with its own
    latency sample (unbatcher.rs:96-106 fan-out)."""

    async def main():
        config = Config(
            n=3, f=1,
            gc_interval_ms=25,
            tempo_detached_send_interval_ms=25,
            executor_executed_notification_interval_ms=25,
        )
        handles, client_addr, _ = await _boot_cluster(Tempo, config)
        workload = Workload(
            shard_count=1,
            key_gen=ConflictPool(conflict_rate=50, pool_size=1),
            keys_per_command=1,
            commands_per_client=COMMANDS,
            payload_size=1,
        )
        h0 = handles[0]
        cids = [1, 2, 3, 4]
        res = await run_client(
            cids,
            {0: client_addr[h0.process_id]},
            {0: h0.process_id},
            workload,
            batch_max_size=len(cids),
            batch_max_delay_ms=20,
            command_timeout_s=30,
        )
        assert all(
            len(d.latency_data()) == COMMANDS for d in res.data.values()
        )
        total = COMMANDS * len(cids)
        assert 0 < res.submits < total, (
            f"batching never merged: {res.submits} submits / {total} cmds"
        )
        for h in handles:
            await h.stop()

    asyncio.run(main())


def test_run_tempo_workers():
    """Worker axis (run/mod.rs:575-849 runs workers=2-4): protocol
    messages route to one of W cooperative workers by MessageIndex —
    dot messages shift past the two reserved workers, GC stays on
    worker 0, clock-bump traffic on worker 1 — with submits pre-dotted
    by the server-side dot generator so a dot's lifetime stays on one
    worker. Full-stack invariants must hold unchanged."""
    _run(
        Tempo,
        Config(n=3, f=1, tempo_detached_send_interval_ms=25),
        workers=3,
    )


def test_run_atlas_workers():
    _run(Atlas, Config(n=3, f=1), workers=2)


def test_run_fpaxos_workers():
    """Leader-based routing: submits and forwards pin to the leader
    worker, accepts/chosen to the acceptor worker, commanders shift by
    slot (fpaxos.rs:383-453)."""
    _run(FPaxos, Config(n=3, f=1, leader=1), workers=4)


def test_run_tempo_table_executor_pool():
    """Table-executor pool (workers × executors like the reference's
    2-4 × 1-3 shapes): multi-key commands split keys across pool
    members; the shared stability-count map (the reference's SharedMap,
    executor.rs:318-330) lets rifls complete across members."""

    async def main():
        config = Config(
            n=3, f=1,
            executor_monitor_execution_order=True,
            gc_interval_ms=25,
            tempo_detached_send_interval_ms=25,
            executor_executed_notification_interval_ms=25,
        )
        handles, client_addr, _ = await _boot_cluster(
            Tempo, config, workers=2, executors=2
        )
        workload = Workload(
            shard_count=1,
            key_gen=ConflictPool(conflict_rate=50, pool_size=4),
            keys_per_command=2,
            commands_per_client=COMMANDS,
            payload_size=1,
        )
        h0 = handles[0]
        res = await run_client(
            [1, 2],
            {0: client_addr[h0.process_id]},
            {0: h0.process_id},
            workload,
            command_timeout_s=30,
        )
        assert all(
            len(d.latency_data()) == COMMANDS for d in res.data.values()
        )
        for h in handles:
            monitors = h.monitors()
            assert len(monitors) == 2
            keys0 = set(monitors[0].keys())
            keys1 = set(monitors[1].keys())
            assert keys0.isdisjoint(keys1)
            # multi-key commands spread over the pool: the shared
            # count map must have drained (every rifl completed)
            assert not h.executors[0].rifl_to_stable_count
            assert (
                h.executors[0].rifl_to_stable_count
                is h.executors[1].rifl_to_stable_count
            )
        for h in handles:
            await h.stop()

    asyncio.run(main())


def test_run_atlas_graph_executor_pool():
    """Graph-executor pool, single shard: the reference's
    executor-0-runs-the-graph split (graph/mod.rs:54-67) routes every
    Add to member 0, so all execution (and the monitor) lives there
    while member 1 idles; full-stack invariants hold unchanged."""
    _run(Atlas, Config(n=3, f=1), workers=2, executors=2)

    async def check():
        config = Config(
            n=3, f=1,
            executor_monitor_execution_order=True,
            gc_interval_ms=25,
            executor_executed_notification_interval_ms=25,
        )
        handles, client_addr, _ = await _boot_cluster(
            Atlas, config, executors=2
        )
        workload = Workload(
            shard_count=1,
            key_gen=ConflictPool(conflict_rate=50, pool_size=2),
            keys_per_command=2,
            commands_per_client=COMMANDS,
            payload_size=1,
        )
        h0 = handles[0]
        res = await run_client(
            [1, 2], {0: client_addr[h0.process_id]}, {0: h0.process_id},
            workload, command_timeout_s=30,
        )
        assert all(
            len(d.latency_data()) == COMMANDS for d in res.data.values()
        )
        for h in handles:
            main, secondary = h.executors
            assert main.vertex_index is secondary.vertex_index, (
                "pool members must share the vertex index"
            )
            assert not secondary.monitor().keys(), (
                "secondary executor must never execute commands"
            )
        for h in handles:
            await h.stop()

    asyncio.run(check())


def test_run_atlas_partial_graph_executor_pool():
    """Graph-executor pool under partial replication: cross-shard
    Request traffic routes to the secondary executor, which answers
    from the shared vertex index (or its Executed-synced clock copy,
    mod.rs:199-213,279-408); every command completes and per-shard
    execution orders agree across replicas."""
    _run(
        Atlas,
        Config(n=3, f=1, shard_count=2),
        executors=2,
    )


def test_run_tempo_multiplexing():
    """Connection multiplexing (run/mod.rs:113, task/server/mod.rs:
    226-310): three TCP connections per peer with sends spread
    round-robin; cross-connection ordering is not guaranteed (the
    reference picks writers at random) and the protocols' buffered
    paths absorb it — full-stack invariants hold unchanged."""

    async def main():
        config = Config(
            n=3, f=1,
            executor_monitor_execution_order=True,
            gc_interval_ms=25,
            tempo_detached_send_interval_ms=25,
            executor_executed_notification_interval_ms=25,
        )
        handles, client_addr, _ = await _boot_cluster(
            Tempo, config, multiplexing=3
        )
        workload = Workload(
            shard_count=1,
            key_gen=ConflictPool(conflict_rate=50, pool_size=1),
            keys_per_command=2,
            commands_per_client=COMMANDS,
            payload_size=1,
        )
        h0 = handles[0]
        res = await run_client(
            [1, 2],
            {0: client_addr[h0.process_id]},
            {0: h0.process_id},
            workload,
            command_timeout_s=30,
        )
        assert all(
            len(d.latency_data()) == COMMANDS for d in res.data.values()
        )
        monitors = {h.process_id: h.monitors()[0] for h in handles}
        check_monitors(monitors)
        for h in handles:
            await h.stop()

    asyncio.run(main())


def test_run_tempo_atomic_workers():
    """The native atomic key clocks under the worker axis — the
    reference's TempoAtomic shape (workers share clock state through
    the C++ CAS map; common/table/clocks/keys/atomic.rs:13-90)."""
    from fantoch_tpu.native.keyclocks import available
    from fantoch_tpu.protocol import TempoAtomic

    if not available():
        pytest.skip("native toolchain unavailable")
    _run(
        TempoAtomic,
        Config(n=3, f=1, tempo_detached_send_interval_ms=25),
        workers=3,
    )

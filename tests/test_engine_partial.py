"""Device-engine partial-replication (multi-shard) differential tests.

The oracle Runner already supports shard_count > 1 (test_sim_partial.py
validates it); here the device twin — TempoPartialDev plus the engine
core's parts-counting client completion — must reproduce the oracle on
the same DeviceStream workload: commands draw ``keys_per_command``
keys from the shared counter stream, each key routed to shard
``key_hash(str(key)) % shard_count`` (client/workload.py:106-107), so
some commands stay single-shard and others span shards — both the
MForwardSubmit/MShardCommit aggregation (partial.rs) and the
StableAtShard executor protocol (executor/table) are exercised.

Multi-shard layouts place co-region processes of different shards at
~0 ms, so schedules are tie-heavy; both sides order same-instant
messages by (src, per-channel counter), and the assertions cover the
schedule-independent outcomes exactly (completion totals, stability
accounting) with latency means exact where the tie orders agree.
"""

import pytest

from fantoch_tpu.client import DeviceStream, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.protocols import AtlasPartialDev, TempoPartialDev
from fantoch_tpu.protocol import Atlas, Tempo
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

COMMANDS = 10
CPR = 1


def partial_config(n, f, shards, tempo=True):
    kw = dict(
        n=n,
        f=f,
        shard_count=shards,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        executor_cleanup_interval_ms=100,
    )
    if tempo:
        kw["tempo_detached_send_interval_ms"] = 100
    return Config(**kw)


def run_oracle(config, regions, conflict, pool, kpc, commands=COMMANDS,
               cpr=CPR, oracle_cls=Tempo):
    planet = Planet.new()
    wl = Workload(
        shard_count=config.shard_count,
        key_gen=DeviceStream(conflict_rate=conflict, pool_size=pool),
        keys_per_command=kpc,
        commands_per_client=commands,
        payload_size=0,
    )
    runner = Runner(
        oracle_cls, planet, config, wl, cpr, regions, list(regions)
    )
    metrics, _, lat = runner.run(extra_sim_time_ms=1500)
    fast = slow = stable = 0
    for pm, _em in metrics.values():
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
        stable += pm.get_aggregated(ProtocolMetricsKind.STABLE) or 0
    return lat, fast, slow, stable


def run_engine(config, regions, conflict, pool, kpc, commands=COMMANDS,
               cpr=CPR, dev_cls=TempoPartialDev):
    planet = Planet.new()
    n, S = config.n, config.shard_count
    clients = cpr * len(regions)
    dev = dev_cls(
        keys=pool + clients + 1, shards=S, keys_per_cmd=kpc
    )
    total = commands * clients
    dims = EngineDims.for_partial(
        dev, n, clients, total, regions=len(regions)
    )
    spec = make_lane(
        dev,
        planet,
        config,
        conflict_rate=conflict,
        pool_size=pool,
        commands_per_client=commands,
        clients_per_region=cpr,
        process_regions=regions,
        client_regions=regions,
        dims=dims,
    )
    return dev, run_lanes(dev, dims, [spec])[0]


@pytest.mark.parametrize(
    "n,f,shards,conflict,pool,kpc",
    [
        (3, 1, 2, 0, 1, 1),    # single-key commands: shard routing only
        (3, 1, 2, 100, 4, 2),  # shared pool: multi-shard + conflicts
        # 3 shards, mixed private/pool stream (slow: ~90 s on CPU; the
        # slow tier also covers shards 3-4 at reference scale)
        pytest.param(3, 1, 3, 50, 4, 2, marks=pytest.mark.slow),
    ],
)
def test_engine_partial_matches_oracle(n, f, shards, conflict, pool, kpc):
    config = partial_config(n, f, shards)
    regions = Planet.new().regions()[:n]
    oracle_lat, fast, slow, stable = run_oracle(
        config, regions, conflict, pool, kpc
    )
    _dev, res = run_engine(config, regions, conflict, pool, kpc)
    assert not res.err, res.err_cause
    total = COMMANDS * CPR * n

    # every client drains its budget with per-part aggregation
    for region in regions:
        issued, _hist = oracle_lat[region]
        assert res.issued(region) == CPR * COMMANDS
    # commits: once per touched shard; identical streams ⇒ identical
    # totals on both sides
    dev_fast = int(res.protocol_metrics["fast_path"].sum())
    dev_slow = int(res.protocol_metrics["slow_path"].sum())
    assert total <= dev_fast + dev_slow <= total * shards
    assert dev_fast + dev_slow == fast + slow
    # stability accounting: n processes GC each dot at its shard
    assert int(res.protocol_metrics["stable"].sum()) == stable == n * total

    for region in regions:
        _issued, hist = oracle_lat[region]
        dev_mean = res.latency_mean(region)
        assert dev_mean == hist.mean(), (
            region, dev_mean, hist.mean()
        )


@pytest.mark.parametrize(
    "n,f,shards,conflict,pool,kpc",
    [
        (3, 1, 2, 100, 4, 2),  # shared pool: cross-shard deps + requests
        # 3 shards, mixed private/pool stream (slow tier)
        pytest.param(3, 1, 3, 50, 4, 2, marks=pytest.mark.slow),
    ],
)
def test_engine_atlas_partial_matches_oracle(n, f, shards, conflict,
                                             pool, kpc):
    """Atlas partial replication: shard-union dep aggregation plus the
    graph executor's cross-shard Request/RequestReply protocol
    (executor/graph/mod.rs:279-408)."""
    config = partial_config(n, f, shards, tempo=False)
    regions = Planet.new().regions()[:n]
    oracle_lat, fast, slow, stable = run_oracle(
        config, regions, conflict, pool, kpc, oracle_cls=Atlas
    )
    _dev, res = run_engine(
        config, regions, conflict, pool, kpc, dev_cls=AtlasPartialDev
    )
    assert not res.err, res.err_cause
    total = COMMANDS * CPR * n

    for region in regions:
        assert res.issued(region) == CPR * COMMANDS
    dev_fast = int(res.protocol_metrics["fast_path"].sum())
    dev_slow = int(res.protocol_metrics["slow_path"].sum())
    assert total <= dev_fast + dev_slow <= total * shards
    assert dev_fast + dev_slow == fast + slow
    assert int(res.protocol_metrics["stable"].sum()) == stable == n * total

    for region in regions:
        _issued, hist = oracle_lat[region]
        dev_mean = res.latency_mean(region)
        assert dev_mean == hist.mean(), (
            region, dev_mean, hist.mean()
        )


@pytest.mark.slow
@pytest.mark.parametrize(
    "n,f,shards,conflict,dev_cls,oracle_cls",
    [
        # the reference's partial run tests reach shard_count 4 with
        # 100-command loads (fantoch/src/run/mod.rs:575-849 shapes);
        # n=5 exercises quorums the n=3 quick tier cannot
        (5, 1, 3, 50, TempoPartialDev, Tempo),
        (5, 1, 4, 50, TempoPartialDev, Tempo),
        (3, 1, 4, 50, AtlasPartialDev, Atlas),
        (5, 1, 3, 50, AtlasPartialDev, Atlas),
    ],
)
def test_engine_partial_reference_scale(n, f, shards, conflict, dev_cls,
                                        oracle_cls):
    """Reference-scale device partial replication: 100 commands per
    client over up to 4 shards. Big schedules are not guaranteed
    tie-free, so this tier asserts the protocol invariants plus
    latency-mean closeness; exactness stays the quick tier's job."""
    commands, pool, kpc = 100, 4, 2
    tempo = oracle_cls is Tempo
    config = partial_config(n, f, shards, tempo=tempo)
    regions = Planet.new().regions()[:n]
    oracle_lat, fast, slow, _stable = run_oracle(
        config, regions, conflict, pool, kpc, commands=commands,
        oracle_cls=oracle_cls,
    )
    _dev, res = run_engine(
        config, regions, conflict, pool, kpc, commands=commands,
        dev_cls=dev_cls,
    )
    assert not res.err, res.err_cause
    total = commands * CPR * n
    for region in regions:
        assert res.issued(region) == CPR * commands
    dev_fast = int(res.protocol_metrics["fast_path"].sum())
    dev_slow = int(res.protocol_metrics["slow_path"].sum())
    assert total <= dev_fast + dev_slow <= total * shards
    assert dev_fast + dev_slow == fast + slow
    assert int(res.protocol_metrics["stable"].sum()) == n * total
    for region in regions:
        _issued, hist = oracle_lat[region]
        assert abs(res.latency_mean(region) - hist.mean()) <= (
            0.1 * hist.mean()
        )


def test_engine_tempo_partial_reorder_invariants():
    """Message reordering (delay ×U(0,10)) over the multi-shard engine:
    exactness is out of scope on randomized schedules, but the
    readiness gates (MCollect window, commit-overtakes-collect,
    buffered MBump, StableAtShard buffering) must absorb every
    overtake: the lane completes cleanly with full GC."""
    n, shards, conflict, pool, kpc = 3, 2, 100, 4, 2
    config = partial_config(n, 1, shards)
    regions = Planet.new().regions()[:n]
    planet = Planet.new()
    clients = CPR * n
    dev = TempoPartialDev(
        keys=pool + clients + 1, shards=shards, keys_per_cmd=kpc
    )
    total = COMMANDS * clients
    dims = EngineDims.for_partial(dev, n, clients, total)
    spec = make_lane(
        dev,
        planet,
        config,
        conflict_rate=conflict,
        pool_size=pool,
        commands_per_client=COMMANDS,
        clients_per_region=CPR,
        process_regions=regions,
        client_regions=regions,
        dims=dims,
        extra_time_ms=30_000,
        seed=5,
        reorder=True,
    )
    res = run_lanes(dev, dims, [spec])[0]
    assert not res.err, res.err_cause
    assert res.completed == total
    assert int(res.protocol_metrics["stable"].sum()) == n * total

"""Device-engine Atlas/EPaxos differential tests.

Same bar the Tempo engine tests set: on tie-free schedules the array
engine reproduces the host oracle *exactly* — per-region latency means,
fast/slow-path counts, GC stable totals. Under same-instant concurrency
tie orders legitimately differ (the reference leaves heap-tie order
unspecified, fantoch/src/sim/schedule.rs:109-119), so those configs
assert protocol invariants plus closeness of means.

Conflict rates are restricted to {0, 100} because intermediate rates
draw different PRNG streams host vs device.
"""

import pytest

from fantoch_tpu.client import ConflictPool, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.protocols import AtlasDev, EPaxosDev
from fantoch_tpu.protocol import Atlas, EPaxos
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

COMMANDS = 30
ORACLES = {"atlas": Atlas, "epaxos": EPaxos}
DEVS = {"atlas": AtlasDev, "epaxos": EPaxosDev}


def run_oracle(proto, config, regions, conflict, commands, cpr):
    planet = Planet.new()
    workload = Workload(
        shard_count=1,
        key_gen=ConflictPool(conflict_rate=conflict, pool_size=1),
        keys_per_command=1,
        commands_per_client=commands,
        payload_size=0,
    )
    runner = Runner(
        ORACLES[proto], planet, config, workload, cpr, regions, list(regions)
    )
    metrics, _, latencies = runner.run(extra_sim_time_ms=1000)
    fast = slow = stable = 0
    for pm, _em in metrics.values():
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
        stable += pm.get_aggregated(ProtocolMetricsKind.STABLE) or 0
    return latencies, fast, slow, stable


def run_engine(proto, config, regions, conflict, commands, cpr):
    planet = Planet.new()
    clients = cpr * len(regions)
    dev = DEVS[proto](keys=1 + clients)
    total = commands * clients
    dims = EngineDims.for_protocol(
        dev,
        n=config.n,
        clients=clients,
        payload=dev.payload_width(config.n),
        total_commands=total,
        dot_slots=total + 1,
        regions=len(regions),
    )
    spec = make_lane(
        dev,
        planet,
        config,
        conflict_rate=conflict,
        pool_size=1,
        commands_per_client=commands,
        clients_per_region=cpr,
        process_regions=regions,
        client_regions=regions,
        dims=dims,
    )
    return run_lanes(dev, dims, [spec])[0]


@pytest.mark.parametrize(
    "proto,n,f,conflict,commands,cpr",
    [
        ("atlas", 3, 1, 100, 30, 1),
        ("atlas", 3, 1, 0, 30, 2),
        ("atlas", 5, 2, 100, 10, 1),
        ("epaxos", 3, 1, 100, 30, 1),
        ("epaxos", 3, 1, 0, 30, 2),
        ("epaxos", 5, 2, 100, 10, 1),
        # reference sim_test scale (mod.rs:639-705: 100 commands)
        pytest.param("atlas", 3, 1, 100, 100, 1,
                     marks=pytest.mark.slow),
        pytest.param("atlas", 5, 2, 100, 100, 1,
                     marks=pytest.mark.slow),
        pytest.param("epaxos", 3, 1, 100, 100, 1,
                     marks=pytest.mark.slow),
        pytest.param("epaxos", 5, 2, 100, 100, 1,
                     marks=pytest.mark.slow),
    ],
)
def test_engine_matches_oracle_exactly(proto, n, f, conflict, commands, cpr):
    """Tie-free schedules: every metric matches the oracle exactly."""
    config = Config(n=n, f=f, gc_interval_ms=100)
    regions = Planet.new().regions()[:n]
    oracle_lat, fast, slow, stable = run_oracle(
        proto, config, regions, conflict, commands, cpr
    )
    res = run_engine(proto, config, regions, conflict, commands, cpr)
    assert not res.err
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    assert int(res.protocol_metrics["stable"].sum()) == stable
    for region in regions:
        _issued, hist = oracle_lat[region]
        assert res.latency_mean(region) == hist.mean(), region
    # threshold-union with f=1 (Atlas) / a single reporter (EPaxos n=3)
    # always passes: 100% fast path (protocol/mod.rs:116-167)
    if (n, f) == (3, 1):
        assert slow == 0


@pytest.mark.parametrize("proto", ["atlas", "epaxos"])
def test_engine_concurrent_invariants(proto):
    """Same-instant concurrency: assert invariants + closeness."""
    n, f, conflict, commands, cpr = 5, 2, 100, 20, 2
    config = Config(n=n, f=f, gc_interval_ms=100)
    regions = Planet.new().regions()[:n]
    oracle_lat, fast, slow, stable = run_oracle(
        proto, config, regions, conflict, commands, cpr
    )
    res = run_engine(proto, config, regions, conflict, commands, cpr)
    assert not res.err
    total_commits = commands * cpr * n
    dev_fast = int(res.protocol_metrics["fast_path"].sum())
    dev_slow = int(res.protocol_metrics["slow_path"].sum())
    assert dev_fast + dev_slow == total_commits == fast + slow
    assert int(res.protocol_metrics["stable"].sum()) == n * total_commits
    for region in regions:
        _issued, hist = oracle_lat[region]
        assert res.issued(region) == commands * cpr
        assert abs(res.latency_mean(region) - hist.mean()) <= 0.1 * hist.mean()

"""Campaign manager (fantoch_tpu/campaign): journal-backed resume.

Default tier: sweep-campaign interrupted-resume writes a results.jsonl
byte-identical to an uninterrupted control run (the compiled Basic
runner the suite already shares), campaign-directory refusal rules, and
fuzz-plan resume determinism (the journaled generator position draws
the identical remaining plans — host-only, no device). Slow tier: a
fuzz campaign on the real monitored pipeline, including the
injected-bug artifact surviving an interruption and replaying after
resume.
"""

import json
import os

import pytest

from fantoch_tpu.campaign import (
    CampaignError,
    campaign_from_json,
    run_campaign,
)
from fantoch_tpu.mc.fuzz import (
    FuzzSpec,
    draw_plans,
    plan_rng,
    point_config,
    point_protocol,
    restore_rng,
    rng_state,
)

# mirrors tests/test_sweep_sharded.py shapes so the campaign batches
# reuse the suite's compiled Basic segment runner. scan_window=1 pins
# the per-segment ladder the stop_after_segments interruption tests
# count on (the default window would finish these tiny batches before
# the first boundary); window-granular campaigns are pinned in
# tests/test_scan_window.py.
SWEEP_GRID = {
    "kind": "sweep",
    "protocols": ["basic"],
    "ns": [3],
    "conflicts": [0, 100],
    "subsets": 2,
    "commands_per_client": 2,
    "batch_lanes": 2,
    "segment_steps": 8,
    "scan_window": 1,
}


def test_campaign_spec_round_trip_and_validation():
    spec = campaign_from_json(SWEEP_GRID)
    assert campaign_from_json(spec.to_json()) == spec
    with pytest.raises(CampaignError, match="kind"):
        campaign_from_json({"kind": "nope"})
    with pytest.raises(CampaignError, match="protocol"):
        campaign_from_json(dict(SWEEP_GRID, protocols=["nope"]))
    with pytest.raises(CampaignError, match="field"):
        campaign_from_json(dict(SWEEP_GRID, bogus=1))


def test_sweep_campaign_resume_byte_identical(tmp_path):
    spec = campaign_from_json(SWEEP_GRID)
    ctrl = run_campaign(str(tmp_path / "ctrl"), spec)
    assert ctrl["done"] and ctrl["errors"] == 0

    intr_dir = str(tmp_path / "intr")
    s1 = run_campaign(intr_dir, spec, stop_after_segments=1)
    assert not s1["done"] and s1["interrupted"] == "segment-limit"
    import glob

    assert glob.glob(os.path.join(intr_dir, "ckpt", "*", "manifest.json"))
    s2 = run_campaign(intr_dir, resume=True)
    assert s2["done"]

    with open(os.path.join(str(tmp_path / "ctrl"), "results.jsonl"), "rb") as fh:
        control_bytes = fh.read()
    with open(os.path.join(intr_dir, "results.jsonl"), "rb") as fh:
        resumed_bytes = fh.read()
    assert control_bytes == resumed_bytes
    assert control_bytes, "results must not be empty"


def test_campaign_budget_makes_progress_and_converges(tmp_path):
    # budget 0 = at least one unit of progress per invocation; repeated
    # budgeted invocations must converge to done
    spec = campaign_from_json(SWEEP_GRID)
    path = str(tmp_path / "c")
    summary = run_campaign(path, spec, budget_s=0.0)
    invocations = 1
    while not summary["done"]:
        summary = run_campaign(path, resume=True, budget_s=0.0)
        invocations += 1
        assert invocations < 50, "budgeted campaign failed to converge"
    assert summary["batches_done"] == summary["batches_total"] == 2
    ctrl = run_campaign(str(tmp_path / "ctrl"), spec)
    with open(os.path.join(path, "results.jsonl"), "rb") as fh:
        a = fh.read()
    with open(os.path.join(str(tmp_path / "ctrl"), "results.jsonl"), "rb") as fh:
        b = fh.read()
    assert a == b


def test_campaign_dir_refusals(tmp_path):
    with pytest.raises(CampaignError, match="resume"):
        run_campaign(str(tmp_path / "missing"), resume=True)
    spec = campaign_from_json(SWEEP_GRID)
    path = str(tmp_path / "c")
    run_campaign(path, spec, stop_after_segments=1)
    other = campaign_from_json(dict(SWEEP_GRID, conflicts=[0, 50]))
    with pytest.raises(CampaignError, match="different campaign"):
        run_campaign(path, other)
    with pytest.raises(CampaignError, match="disagrees"):
        run_campaign(path, other, resume=True)


def test_campaign_journal_tolerates_torn_final_line(tmp_path):
    spec = campaign_from_json(SWEEP_GRID)
    path = str(tmp_path / "c")
    run_campaign(path, spec)
    # tear the final journal line (a SIGKILL mid-append); the torn unit
    # simply reruns and the campaign still completes identically
    jpath = os.path.join(path, "journal.jsonl")
    with open(jpath) as fh:
        lines = fh.readlines()
    with open(jpath, "w") as fh:
        fh.writelines(lines[:-1])
        fh.write(lines[-1][: len(lines[-1]) // 2])
    os.remove(os.path.join(path, "results.jsonl"))
    summary = run_campaign(path, resume=True)
    assert summary["done"]
    ctrl = run_campaign(str(tmp_path / "ctrl"), spec)
    with open(os.path.join(path, "results.jsonl"), "rb") as fh:
        a = fh.read()
    with open(os.path.join(str(tmp_path / "ctrl"), "results.jsonl"), "rb") as fh:
        b = fh.read()
    assert a == b


def test_campaign_stops_on_sigterm_and_resumes_identically(tmp_path):
    """A SIGTERM mid-campaign stops at the next boundary with state
    durable (run_sweep flushes mid-segment; the manager stops between
    units); resuming completes with byte-identical results."""
    import signal
    import threading

    spec = campaign_from_json(SWEEP_GRID)
    ctrl = run_campaign(str(tmp_path / "ctrl"), spec)
    assert ctrl["done"]

    path = str(tmp_path / "intr")
    timer = threading.Timer(
        0.05, lambda: os.kill(os.getpid(), signal.SIGTERM)
    )
    timer.start()
    try:
        summary = run_campaign(path, spec)
    finally:
        timer.cancel()
    # wherever the signal landed — mid-segment (SweepInterrupted),
    # between units, or after the last unit — the campaign either
    # stopped naming the signal or had already finished; either way
    # resuming must converge to the identical results
    if not summary["done"]:
        assert "signal" in summary["interrupted"], summary
        summary = run_campaign(path, resume=True)
    assert summary["done"]
    with open(os.path.join(path, "results.jsonl"), "rb") as fh:
        a = fh.read()
    with open(os.path.join(str(tmp_path / "ctrl"), "results.jsonl"), "rb") as fh:
        b = fh.read()
    assert a == b


# ----------------------------------------------------------------------
# fuzz-campaign resume determinism
# ----------------------------------------------------------------------


def test_fuzz_plans_resume_identical_after_journal_round_trip():
    """The satellite contract: a resumed campaign draws the identical
    remaining per-lane plans because the root generator's position is
    journaled (JSON round-trip included), not recomputed."""
    spec = FuzzSpec(protocol="tempo", n=3, schedules=12, seed=11)
    config, dev = point_config(spec), point_protocol(spec)
    reference = draw_plans(spec, config, dev)

    rng = plan_rng(spec)
    first = draw_plans(spec, config, dev, count=5, rng=rng)
    journaled = json.loads(json.dumps(rng_state(rng)))  # the journal hop
    rest = draw_plans(
        spec, config, dev, count=7, rng=restore_rng(journaled)
    )
    assert first + rest == reference

    # and the default (non-resumable) call still draws the same plans
    assert draw_plans(spec, config, dev) == reference


@pytest.mark.slow
def test_fuzz_campaign_resume_accumulates_coverage(tmp_path):
    grid = campaign_from_json(
        {
            "kind": "fuzz",
            "protocols": ["tempo"],
            "ns": [3],
            "schedules": 8,
            "chunk": 4,
            "commands_per_client": 5,
            "seed": 7,
            "confirm": False,
        }
    )
    path = str(tmp_path / "c")
    s1 = run_campaign(path, grid, budget_s=0.0)
    assert not s1["done"]
    assert s1["points"]["tempo/n3"]["tried"] == 4
    s2 = run_campaign(path, resume=True)
    assert s2["done"]
    assert s2["points"]["tempo/n3"]["tried"] == 8

    ctrl = run_campaign(str(tmp_path / "ctrl"), grid)
    assert s2["points"] == ctrl["points"]


@pytest.mark.slow
def test_fuzz_campaign_artifact_survives_interruption(tmp_path):
    """An artifact confirmed+shrunk before the interruption is already
    on disk, still present after resume, and replays."""
    from fantoch_tpu.mc.fuzz import load_artifact, replay_artifact

    grid = campaign_from_json(
        {
            "kind": "fuzz",
            "protocols": ["tempo"],
            "ns": [3],
            "schedules": 4,
            "chunk": 2,
            "commands_per_client": 5,
            "seed": 3,
            "crash_share": 0.0,
            "drop_share": 0.0,
            "max_confirm": 1,
            "shrink_budget": 80,
            "inject_bug": True,
        }
    )
    path = str(tmp_path / "c")
    s1 = run_campaign(path, grid, budget_s=0.0)  # exactly one chunk
    assert not s1["done"]
    point = s1["points"]["tempo/n3"]
    assert point["tried"] == 2
    assert point["confirmed"] >= 1, point
    arts = point["artifacts"]
    assert arts, "confirmed violation must persist an artifact"
    apath = os.path.join(path, arts[0])
    assert os.path.exists(apath)

    s2 = run_campaign(path, resume=True)
    assert s2["done"]
    assert os.path.exists(apath), "artifact lost across resume"
    rep = replay_artifact(load_artifact(apath))
    assert rep["reproduced"], rep

"""Model-checker tests: exhaustive interleaving exploration on tiny
conflicting workloads (the working analog of fantoch_mc's intended
checks, fantoch_mc/src/lib.rs:84-238).
"""

import pytest

from fantoch_tpu.core import Config
from fantoch_tpu.mc import ModelChecker
from fantoch_tpu.protocol import Atlas, Caesar, EPaxos, FPaxos, Tempo


@pytest.mark.parametrize(
    "protocol_cls,kw,max_states",
    [
        (Tempo, dict(tempo_detached_send_interval_ms=1000), 5_000),
        (Atlas, {}, 5_000),
        (EPaxos, {}, 5_000),
        (FPaxos, dict(leader=1), 5_000),
        # Caesar's wait condition defers propose replies, deepening the
        # branches the DFS must drive to quiescence — cap the explored
        # states lower and assert the explored prefix instead of
        # skipping the protocol (the quiescent floor below still holds)
        (Caesar, dict(caesar_wait_condition=True), 2_000),
    ],
)
def test_two_conflicting_commands_all_interleavings(
    protocol_cls, kw, max_states
):
    """2 clients × 1 command on one conflicting key, n=3: every
    explored delivery interleaving must quiesce with identical,
    exactly-once execution orders on every process."""
    mc = ModelChecker(
        protocol_cls,
        Config(n=3, f=1, **kw),
        clients=2,
        commands_per_client=1,
        max_states=max_states,
    )
    result = mc.run()
    assert result.ok, result.violation
    # the full interleaving space is factorial; the bounded DFS still
    # drives hundreds of complete schedules to quiescence and checks
    # every one (truncation of the remaining tree is expected)
    assert result.quiescent > 100, result.quiescent


def test_detects_divergence():
    """Sanity: the checker is not vacuous — a protocol that executes at
    commit (skipping the ordering layer) must be caught."""

    class TempoUnordered(Tempo):
        pass

    mc = ModelChecker(
        TempoUnordered,
        Config(n=3, f=1, execute_at_commit=True),
        clients=2,
        commands_per_client=1,
        max_states=50_000,
    )
    result = mc.run()
    assert not result.ok, "execute_at_commit must break agreement"

"""Experiment-orchestration tests: the full fantoch_exp-style loop —
real server and client subprocesses started from generated CLI args on
the Local testbed, metrics pulled into an experiment dir
(fantoch_exp/src/bench.rs:43-187).
"""

from __future__ import annotations

from fantoch_tpu.exp import (
    ClientConfig,
    ExperimentConfig,
    ProtocolConfig,
    bench_experiment,
)
from fantoch_tpu.exp.bench import load_experiment
from fantoch_tpu.protocol.base import ProtocolMetricsKind


def test_to_args_roundtrip():
    cfg = ProtocolConfig(
        protocol="tempo", process_id=1, shard_id=0, n=3, f=1,
        port=4000, client_port=5000,
        addresses={2: ("127.0.0.1", 4001), 3: ("127.0.0.1", 4002)},
        metrics_file="/tmp/m1",
    )
    args = cfg.to_args()
    assert args[0] == "proc"
    assert "--addresses" in args
    assert args[args.index("--addresses") + 1] == (
        "2=127.0.0.1:4001,3=127.0.0.1:4002"
    )
    ccfg = ClientConfig(
        ids=(1, 4), addresses={0: ("127.0.0.1", 5000)},
        shard_processes={0: 1}, commands=10,
    )
    cargs = ccfg.to_args()
    assert cargs[0] == "client"
    assert cargs[cargs.index("--ids") + 1] == "1-4"


def test_local_experiment_tempo(tmp_path):
    exp = ExperimentConfig(
        protocol="tempo", n=3, f=1, shard_count=1,
        clients=3, commands_per_client=5, conflict=50,
    )
    run_dir = bench_experiment(exp, str(tmp_path))
    loaded = load_experiment(run_dir)
    assert loaded["config"]["protocol"] == "tempo"
    # every client group completed its budget
    total = sum(len(v) for v in loaded["clients"].values())
    assert total == 3 * 5
    # per-process metrics pulled for all replicas, with commits recorded
    assert sorted(loaded["metrics"]) == [1, 2, 3]
    fast = slow = 0
    for snap in loaded["metrics"].values():
        pm = snap["protocol"]
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
    assert fast + slow == 15, (fast, slow)

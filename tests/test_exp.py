"""Experiment-orchestration tests: the full fantoch_exp-style loop —
real server and client subprocesses started from generated CLI args
over the testbed machinery (Local directly; Baremetal/SSH through a
local stand-in transport), metrics pulled into an experiment dir
(fantoch_exp/src/bench.rs:43-187, machine.rs, testbed/).
"""

from __future__ import annotations

import json
import os
import stat

from fantoch_tpu.exp import (
    ClientConfig,
    ExperimentConfig,
    LocalMachine,
    ProtocolConfig,
    RunMode,
    SshMachine,
    aws_setup,
    baremetal_setup,
    bench_experiment,
    create_nicknames,
    create_placement,
    local_setup,
)
from fantoch_tpu.exp.bench import load_experiment
from fantoch_tpu.protocol.base import ProtocolMetricsKind


def test_to_args_roundtrip():
    cfg = ProtocolConfig(
        protocol="tempo", process_id=1, shard_id=0, n=3, f=1,
        port=4000, client_port=5000,
        addresses={2: ("127.0.0.1", 4001), 3: ("127.0.0.1", 4002)},
        metrics_file="/tmp/m1",
    )
    args = cfg.to_args()
    assert args[0] == "proc"
    assert "--addresses" in args
    assert args[args.index("--addresses") + 1] == (
        "2=127.0.0.1:4001,3=127.0.0.1:4002"
    )
    ccfg = ClientConfig(
        ids=(1, 4), addresses={0: ("127.0.0.1", 5000)},
        shard_processes={0: 1}, commands=10,
    )
    cargs = ccfg.to_args()
    assert cargs[0] == "client"
    assert cargs[cargs.index("--ids") + 1] == "1-4"


def test_placement_scheme():
    """testbed/mod.rs:80-128's documented example: shard_count=3 over
    [A..E] gives (A,0)->1, (A,1)->6, (A,2)->11, (B,0)->2, ..."""
    placement = create_placement(3, ["A", "B", "C", "D", "E"])
    assert placement[("A", 0)] == (1, 1)
    assert placement[("A", 1)] == (6, 1)
    assert placement[("A", 2)] == (11, 1)
    assert placement[("B", 0)] == (2, 2)
    assert placement[("B", 1)] == (7, 2)
    assert len(placement) == 15


def test_nicknames_roundtrip():
    from fantoch_tpu.exp import Nickname

    nicknames = create_nicknames(2, ["eu", "us"])
    assert [n.to_string() for n in nicknames] == [
        "server_eu_0", "server_eu_1", "client_eu",
        "server_us_0", "server_us_1", "client_us",
    ]
    for n in nicknames:
        back = Nickname.from_string(n.to_string())
        assert (back.region, back.shard_id) == (n.region, n.shard_id)


def test_local_machine_exec_copy(tmp_path):
    m = LocalMachine()
    assert m.ip() == "127.0.0.1"
    assert m.exec("echo hello").strip() == "hello"
    src = tmp_path / "a.txt"
    src.write_text("payload")
    m.copy_to(str(src), str(tmp_path / "b.txt"))
    assert (tmp_path / "b.txt").read_text() == "payload"
    # same-path copies are a no-op, not an error
    m.copy_from(str(src), str(src))


def _fake_transport(tmp_path):
    """A local stand-in for ssh/scp: the ssh binary runs the remote
    command through /bin/sh, the scp binary strips host: prefixes and
    copies — so the full SshMachine path (argv construction, env/cwd
    encoding into the command line, artifact pulling) runs hermetically
    on this host."""
    ssh = tmp_path / "fake_ssh"
    ssh.write_text(
        "#!/usr/bin/env python\n"
        "import subprocess, sys\n"
        "sys.exit(subprocess.call(['/bin/sh', '-c', sys.argv[-1]]))\n"
    )
    scp = tmp_path / "fake_scp"
    scp.write_text(
        "#!/usr/bin/env python\n"
        "import shutil, sys\n"
        "strip = lambda p: p.split(':', 1)[1] if ':' in p and not "
        "p.startswith('/') else p\n"
        "shutil.copy(strip(sys.argv[-2]), strip(sys.argv[-1]))\n"
    )
    for f in (ssh, scp):
        f.chmod(f.stat().st_mode | stat.S_IXUSR)
    return str(ssh), str(scp)


def test_ssh_machine_exec_and_copy(tmp_path):
    ssh, scp = _fake_transport(tmp_path)
    m = SshMachine(
        "10.0.0.7", "ubuntu", ssh_binary=ssh, scp_binary=scp
    )
    assert m.ip() == "10.0.0.7"
    assert m.exec("echo remote").strip() == "remote"
    # env/cwd ride inside the remote command line
    cmd = m.remote_command(
        ["printenv", "MARKER"], env={"MARKER": "x y"}, cwd="/tmp"
    )
    assert cmd == "cd /tmp && env MARKER='x y' printenv MARKER"
    src = tmp_path / "metrics.bin"
    src.write_text("data")
    m.copy_from(str(src), str(tmp_path / "pulled.bin"))
    assert (tmp_path / "pulled.bin").read_text() == "data"


def test_baremetal_and_aws_setup(tmp_path):
    machines_file = tmp_path / "machines"
    machines_file.write_text(
        "\n".join(f"ubuntu@10.0.0.{i}" for i in range(1, 7)) + "\n"
    )
    ms = baremetal_setup(
        ["eu", "us"], 2, str(machines_file), key_path=None
    )
    # nickname order: eu servers (shards 0,1), eu client, us ...
    assert ms.server(1).ip() == "10.0.0.1"  # (eu, shard 0) -> pid 1
    assert ms.server(3).ip() == "10.0.0.2"  # (eu, shard 1) -> pid 3
    assert ms.client("eu").ip() == "10.0.0.3"
    assert ms.server(2).ip() == "10.0.0.4"
    assert ms.vm_count() == 6
    assert all(isinstance(m, SshMachine) for m in ms.vms())

    inventory = tmp_path / "inventory.json"
    inventory.write_text(json.dumps({
        "eu": ["ec2-1", "ec2-2", "ec2-3"],
        "us": ["ec2-4", "ec2-5", "ec2-6"],
    }))
    aws = aws_setup(["eu", "us"], 2, str(inventory))
    assert aws.server(1).ip() == "ec2-1"
    assert aws.client("us").ip() == "ec2-6"


def test_local_experiment_tempo(tmp_path):
    exp = ExperimentConfig(
        protocol="tempo", n=3, f=1, shard_count=1,
        clients=3, commands_per_client=5, conflict=50,
    )
    run_dir = bench_experiment(exp, str(tmp_path))
    loaded = load_experiment(run_dir)
    assert loaded["config"]["protocol"] == "tempo"
    # every client group completed its budget
    total = sum(len(v) for v in loaded["clients"].values())
    assert total == 3 * 5
    # per-process metrics pulled for all replicas, with commits recorded
    assert sorted(loaded["metrics"]) == [1, 2, 3]
    fast = slow = 0
    for snap in loaded["metrics"].values():
        pm = snap["protocol"]
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
    assert fast + slow == 15, (fast, slow)


def test_local_testbed_experiment_with_profile(tmp_path):
    """An explicit local testbed + RunMode.CPROFILE: the experiment
    completes and every client leaves a cProfile artifact (the
    flamegraph/heaptrack analog, lib.rs:26-70)."""
    exp = ExperimentConfig(
        protocol="basic", n=3, f=1, shard_count=1,
        clients=3, commands_per_client=3, conflict=0,
    )
    machines = local_setup(["r1", "r2", "r3"], 1)
    run_dir = bench_experiment(
        exp, str(tmp_path), machines=machines, run_mode=RunMode.CPROFILE
    )
    loaded = load_experiment(run_dir)
    total = sum(len(v) for v in loaded["clients"].values())
    assert total == 3 * 3
    profs = [f for f in os.listdir(run_dir) if f.endswith(".prof")]
    assert any(f.startswith("client_") for f in profs), profs


def test_baremetal_testbed_experiment_fake_ssh(tmp_path):
    """The full baremetal path over the local ssh stand-in: machines
    come from a user@host file, servers get the reference's fixed port
    scheme (config.rs:494-502), commands ride an ssh command line with
    env/cwd encoded, and artifacts are pulled with scp into the
    experiment dir."""
    ssh, scp = _fake_transport(tmp_path)
    workdir = tmp_path / "remote_repo"
    workdir.mkdir()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.symlink(
        os.path.join(repo, "fantoch_tpu"), workdir / "fantoch_tpu"
    )
    machines_file = tmp_path / "machines"
    # every "host" is this machine through the fake transport
    machines_file.write_text("127.0.0.1\n" * 6)
    machines = baremetal_setup(
        ["r1", "r2", "r3"], 1, str(machines_file),
        key_path=None, workdir=str(workdir),
        ssh_binary=ssh, scp_binary=scp,
    )
    exp = ExperimentConfig(
        protocol="basic", n=3, f=1, shard_count=1,
        clients=3, commands_per_client=3, conflict=0,
    )
    run_dir = bench_experiment(exp, str(tmp_path / "out"), machines=machines)
    loaded = load_experiment(run_dir)
    total = sum(len(v) for v in loaded["clients"].values())
    assert total == 3 * 3
    assert sorted(loaded["metrics"]) == [1, 2, 3]

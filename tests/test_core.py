"""L0 tests: ids, commands, kvs, histogram, workload/key-gen statistics.

Mirrors the co-located unit tests in fantoch/src/{id,command,kvs}.rs,
metrics/histogram.rs and client/workload.rs.
"""

import random

from fantoch_tpu.client import Client, ConflictPool, Workload, Zipf
from fantoch_tpu.core import (
    Command,
    DotGen,
    Histogram,
    KVStore,
    Rifl,
    RiflGen,
    SimTime,
    process_ids,
)
from fantoch_tpu.core.kvs import GET, PUT


def test_ids():
    gen = DotGen(3)
    assert (gen.next_id().source, gen.next_id().sequence) == (3, 2)
    assert process_ids(0, 3) == [1, 2, 3]
    assert process_ids(1, 3) == [4, 5, 6]
    assert process_ids(3, 3) == [10, 11, 12]
    assert process_ids(2, 5) == [11, 12, 13, 14, 15]


def test_dot_target_shard():
    from fantoch_tpu.core import Dot

    n = 3
    assert Dot(1, 1).target_shard(n) == 0
    assert Dot(3, 1).target_shard(n) == 0
    assert Dot(4, 1).target_shard(n) == 1
    assert Dot(6, 7).target_shard(n) == 1


def test_command_conflicts():
    # mirrors command.rs:294-338
    rifl = Rifl(1, 1)
    cmd_a = Command(rifl, {0: {"A": [(GET,)]}})
    cmd_b = Command(rifl, {0: {"B": [(GET,)]}})
    cmd_ab = Command(rifl, {0: {"A": [(GET,)], "B": [(GET,)]}})
    assert not cmd_a.conflicts(cmd_b)
    assert cmd_a.conflicts(cmd_ab)
    assert cmd_b.conflicts(cmd_ab)
    assert cmd_a.conflicts(cmd_a)


def test_kvs_flow():
    # mirrors kvs.rs:86-158
    store = KVStore()
    rifl = Rifl(1, 1)
    assert store.execute("x", [(GET,)], rifl) == [None]
    assert store.execute("x", [(PUT, "a")], rifl) == [None]
    assert store.execute("x", [(GET,)], rifl) == ["a"]
    assert store.execute("x", [(PUT, "b")], rifl) == ["a"]
    assert store.execute("x", [(GET,)], rifl) == ["b"]


def test_command_execute():
    store = KVStore()
    rifl = Rifl(1, 1)
    cmd = Command(rifl, {0: {"x": [(PUT, "v")], "y": [(GET,)]}})
    result = cmd.execute(0, store)
    assert result.rifl == rifl
    assert result.results == {"x": [None], "y": [None]}


def test_histogram():
    h = Histogram.from_values([10, 20, 30])
    assert h.mean() == 20.0
    assert h.count() == 3
    assert h.percentile(0.5) == 20.0
    assert h.percentile(0.99) == 30.0
    h2 = Histogram.from_values([10] * 100)
    assert h2.cov() == 0.0


def test_histogram_from_buckets():
    import numpy as np

    buckets = np.zeros(100, dtype=np.int64)
    buckets[10] = 2
    buckets[50] = 2
    h = Histogram.from_buckets(buckets)
    assert h.mean() == 30.0
    assert h.count() == 4


def test_conflict_rate_statistics():
    # mirrors workload.rs:351-398 (reduced sample size)
    for conflict_rate in (1, 2, 10, 50):
        total = 200_000
        workload = Workload(
            shard_count=1,
            key_gen=ConflictPool(conflict_rate=conflict_rate, pool_size=1),
            keys_per_command=1,
            commands_per_client=total,
            payload_size=0,
        )
        rifl_gen = RiflGen(1)
        state = workload.initial_state(1, random.Random(7))
        conflicts = 0
        while True:
            nxt = workload.next_cmd(rifl_gen, state)
            if nxt is None:
                break
            _, cmd = nxt
            if cmd.keys(0) == ["CONFLICT0"]:
                conflicts += 1
        percentage = conflicts * 100 / total
        assert round(percentage) == conflict_rate


def test_zipf_keygen():
    workload = Workload(
        shard_count=1,
        key_gen=Zipf(coefficient=1.0, total_keys_per_shard=100),
        keys_per_command=2,
        commands_per_client=1000,
        payload_size=0,
    )
    rifl_gen = RiflGen(1)
    state = workload.initial_state(1, random.Random(7))
    seen = set()
    while True:
        nxt = workload.next_cmd(rifl_gen, state)
        if nxt is None:
            break
        _, cmd = nxt
        keys = cmd.keys(0)
        assert len(keys) == 2 and len(set(keys)) == 2
        seen.update(int(k) for k in keys)
    assert min(seen) >= 1 and max(seen) <= 100
    # zipf(1.0) concentrates on low ranks
    assert 1 in seen


def test_client_flow():
    # mirrors client/mod.rs:234-302
    workload = Workload(
        shard_count=1,
        key_gen=ConflictPool(conflict_rate=100, pool_size=1),
        keys_per_command=1,
        commands_per_client=2,
        payload_size=100,
    )
    client = Client(1, workload, rng=random.Random(0))
    client.connect({0: 2})
    time = SimTime()
    shard, cmd = client.cmd_send(time)
    assert client.shard_process(shard) == 2
    time.add_millis(10)
    client.cmd_recv(cmd.rifl, time)
    nxt = client.cmd_send(time)
    assert nxt is not None
    _, cmd = nxt
    time.add_millis(5)
    client.cmd_recv(cmd.rifl, time)
    assert client.cmd_send(time) is None
    assert client.finished()
    assert sorted(client.data.latency_data()) == [5000, 10000]
    assert client.data.throughput_data() == [(10, 1), (15, 1)]

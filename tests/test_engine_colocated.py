"""Lookahead-engine regression on a near-zero (co-located) delay matrix.

With inter-process delays below 1 ms the conservative-lookahead bound
degenerates: distinct processes can exchange same-instant messages, so
``make_lane`` falls back to serialized global-time stepping (lookahead
0 + the global-minimum escape hatch; engine/spec.py). This pins the
two properties that fallback must keep:

* correctness — the lane completes every command cleanly (tie order is
  engine-defined on such schedules, so protocol invariants, not oracle
  equality, are the bar);
* boundedness — the lane finishes within a step budget proportional to
  the event count (one delivery per destination per step), instead of
  stalling or spinning. The ~N-fold concurrency loss vs WAN-delay
  lanes is documented in docs/PERF.md.
"""

import numpy as np

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.dims import INF
from fantoch_tpu.engine.protocols import BasicDev

REGIONS = ["colo-a", "colo-b", "colo-c"]
COMMANDS = 10
CPR = 1


def _colocated_planet():
    return Planet.from_latencies(
        {r: {q: 0 for q in REGIONS} for r in REGIONS}
    )


def test_colocated_lane_completes_within_step_budget():
    n = len(REGIONS)
    planet = _colocated_planet()
    config = Config(n=n, f=1, gc_interval_ms=100)
    clients = n * CPR
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        BasicDev,
        n=n,
        clients=clients,
        payload=BasicDev.payload_width(n),
        # the degenerate 0-RTT closed loop queues every remote delivery
        # at one instant — for_protocol's total_commands bound covers it
        total_commands=total,
        dot_slots=total + 1,
        regions=n,
    )
    spec = make_lane(
        BasicDev,
        planet,
        config,
        conflict_rate=100,
        pool_size=1,
        commands_per_client=COMMANDS,
        clients_per_region=CPR,
        process_regions=REGIONS,
        client_regions=REGIONS,
        dims=dims,
        extra_time_ms=500,
    )
    # the fallback actually engaged: off-diagonal lookahead is 0
    la = spec.ctx["lookahead"][:n, :n]
    assert la[~np.eye(n, dtype=bool)].max() == 0
    assert (np.diag(la) >= INF).all()

    res = run_lanes(BasicDev, dims, [spec])[0]
    assert res.err == 0, res.err_cause
    assert res.completed == total
    for r in REGIONS:
        assert res.issued(r) == CPR * COMMANDS
        # co-located everything: the whole run happens at t=0
        assert res.latency_mean(r) == 0.0

    # step budget: serialized stepping handles >= 1 event per step with
    # at most one delivery per destination; every command costs
    # ~2(n-1)+2 messages plus periodic ticks through the extra-time
    # coda. 20x headroom over that event count — regression fails loud
    # if the fallback ever starts spinning without consuming events.
    events = total * (2 * (n - 1) + 2) + 3 * n * 500 // 100
    assert res.steps <= 20 * events, (res.steps, events)

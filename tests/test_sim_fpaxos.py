"""FPaxos whole-protocol simulation tests (mirrors
fantoch_ps/src/protocol/mod.rs sim_fpaxos_* tests)."""

import pytest

from fantoch_tpu.core import Config
from fantoch_tpu.protocol.fpaxos import FPaxos

from harness import sim_test


@pytest.mark.parametrize("n,f,leader", [(3, 1, 1), (5, 1, 1), (5, 2, 1)])
def test_sim_fpaxos(n, f, leader):
    slow_paths = sim_test(FPaxos, Config(n=n, f=f, leader=leader))
    # fpaxos has no fast/slow path distinction; metric stays zero
    assert slow_paths == 0


def test_sim_fpaxos_non_leader_region():
    # leader in a different region than most clients
    slow_paths = sim_test(FPaxos, Config(n=3, f=1, leader=3), seed=7)
    assert slow_paths == 0

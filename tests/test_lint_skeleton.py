"""The GL601-GL604 skeleton family (lint/skeleton.py +
engine/skeleton.py + the run_sweep/aot skeleton marker): taxonomy units
over synthetic plane specs, the unification ledger gate's refusal
semantics, the clean-at-HEAD pins against the checked-in
``lint/skeleton_baseline.json``, byte-exact pack/unpack round-trips,
the GL604 alpha-equivalence pin the whole family exists for, the GL603
amplification budget refusals, and the satellite wiring — the
conditional ``skeleton`` key in AOT signatures and checkpoint meta,
and the halved default scan-window cap for union-packed lanes."""

import json
import os

import numpy as np
import pytest

from fantoch_tpu.engine.skeleton import (
    CASTABLE,
    PRIVATE,
    SHARED,
    SkeletonMismatchError,
    build_skeleton,
    classify_planes,
    pack_ctx,
    pack_state,
    packed_spec,
    skeleton_fingerprint,
    unflatten_planes,
    unpack_ctx,
    unpack_state,
    walk_planes,
)
from fantoch_tpu.lint.report import Finding
from fantoch_tpu.lint.skeleton import (
    DEFAULT_SKELETON_BASELINE,
    amplification_findings,
    attach_reasons,
    gate_skeleton_ledger,
    grid_amplification,
    load_skeleton_baseline,
    norm_grids,
    run_skeleton,
    run_skeleton_selfcheck,
    specs_from_baseline,
    write_skeleton_baseline,
)
from fantoch_tpu.registry import DEV_PROTOCOLS, PARTIAL_DEV_PROTOCOLS

ALL_AUDITS = tuple(DEV_PROTOCOLS) + tuple(
    f"{n}@2shards" for n in PARTIAL_DEV_PROTOCOLS
)


# ----------------------------------------------------------------------
# GL601 taxonomy units (synthetic plane specs — no tracing)
# ----------------------------------------------------------------------


def test_shared_plane_pads_to_elementwise_max():
    entries = classify_planes({
        "a": {"state.x": ((3, 4), "int32")},
        "b": {"state.x": ((5, 2), "int32")},
    })
    ent = entries["state.x"]
    assert ent["verdict"] == SHARED
    assert ent["union"] == {"shape": [5, 4], "dtype": "int32"}


def test_castable_widen_is_lossless_and_order_free():
    for specs in (
        {"a": {"state.x": ((2,), "int16")},
         "b": {"state.x": ((2,), "int32")}},
        {"a": {"state.x": ((2,), "int32")},
         "b": {"state.x": ((2,), "int16")}},
    ):
        ent = classify_planes(specs)["state.x"]
        assert ent["verdict"] == CASTABLE
        assert ent["union"]["dtype"] == "int32"
    # three-way chain widens to the top
    ent = classify_planes({
        "a": {"state.x": ((2,), "int8")},
        "b": {"state.x": ((2,), "int16")},
        "c": {"state.x": ((2,), "int32")},
    })["state.x"]
    assert ent["verdict"] == CASTABLE
    assert ent["union"]["dtype"] == "int32"


def test_no_lossless_widen_is_private():
    # i64 + f32 promote to f64, which cannot hold every i64 — there is
    # no value-preserving union storage, so the plane stays per-audit
    ent = classify_planes({
        "a": {"state.x": ((2,), "int64")},
        "b": {"state.x": ((2,), "float32")},
    })["state.x"]
    assert ent["verdict"] == PRIVATE
    assert "union" not in ent


def test_partial_presence_and_rank_mismatch_are_private():
    entries = classify_planes({
        "a": {"state.only_a": ((2,), "int32"),
              "state.r": ((2, 3), "int32")},
        "b": {"state.r": ((6,), "int32")},
    })
    assert entries["state.only_a"]["verdict"] == PRIVATE
    assert sorted(entries["state.only_a"]["native"]) == ["a"]
    assert entries["state.r"]["verdict"] == PRIVATE  # rank 2 vs rank 1


# ----------------------------------------------------------------------
# GL601 ledger gate units
# ----------------------------------------------------------------------

_GRIDS = {"g": {"audits": ("a", "b"), "max_amplification": 9.0}}


def _entries():
    entries = classify_planes({
        "a": {"state.x": ((3,), "int32")},
        "b": {"state.x": ((5,), "int32")},
    })
    attach_reasons(entries, 2)
    return entries


def _baseline():
    return {
        "audits": ["a", "b"],
        "grids": dict(_GRIDS),
        "planes": {
            k: json.loads(json.dumps(v)) for k, v in _entries().items()
        },
    }


def test_gate_missing_ledger_is_a_bootstrap_finding():
    findings, stale = gate_skeleton_ledger(
        _entries(), ["a", "b"], _GRIDS, {"planes": {}}
    )
    assert len(findings) == 1 and findings[0].rule == "GL601"
    assert findings[0].anchor == "skeleton_baseline"
    assert stale == []


def test_gate_new_plane_and_verdict_drift_fail_both_ways():
    base = _baseline()
    entries = _entries()
    entries["state.y"] = dict(entries["state.x"])
    findings, _ = gate_skeleton_ledger(entries, ["a", "b"], _GRIDS, base)
    assert [f.anchor for f in findings] == ["state.y"]
    assert "NEW state plane" in findings[0].message

    # drift in EITHER direction fails — regenerated deliberately,
    # never absorbed
    entries = _entries()
    entries["state.x"]["verdict"] = PRIVATE
    entries["state.x"].pop("union")
    findings, _ = gate_skeleton_ledger(entries, ["a", "b"], _GRIDS, base)
    assert any("verdict changed" in f.message for f in findings)
    base2 = _baseline()
    base2["planes"]["state.x"]["verdict"] = PRIVATE
    findings, _ = gate_skeleton_ledger(
        _entries(), ["a", "b"], _GRIDS, base2
    )
    assert any("verdict changed" in f.message for f in findings)


def test_gate_union_and_native_drift_fail():
    base = _baseline()
    entries = _entries()
    entries["state.x"]["union"] = {"shape": [7], "dtype": "int32"}
    findings, _ = gate_skeleton_ledger(entries, ["a", "b"], _GRIDS, base)
    assert any("union storage slot changed" in f.message for f in findings)

    # a native drift below the union max leaves the slot intact but
    # still fails, naming the drifted audit
    entries = _entries()
    entries["state.x"]["native"]["a"]["shape"] = [4]
    findings, _ = gate_skeleton_ledger(entries, ["a", "b"], _GRIDS, base)
    msgs = [f.message for f in findings]
    assert any("native spec drift for ['a']" in m for m in msgs)


def test_gate_audit_grid_and_declared_grid_drift_fail():
    base = _baseline()
    findings, _ = gate_skeleton_ledger(
        _entries(), ["a", "b", "c"], _GRIDS, base
    )
    assert any(f.anchor == "audits" for f in findings)

    grids = {"g": {"audits": ("a", "b"), "max_amplification": 99.0}}
    findings, _ = gate_skeleton_ledger(_entries(), ["a", "b"], grids, base)
    assert any(f.anchor == "grids:g" for f in findings)
    # a grid added or removed drifts too
    findings, _ = gate_skeleton_ledger(_entries(), ["a", "b"], {}, base)
    assert any(f.anchor == "grids:g" for f in findings)


def test_gate_reasonless_entry_fails_and_stale_is_advisory():
    base = _baseline()
    base["planes"]["state.x"]["reason"] = ""
    base["planes"]["state.gone"] = dict(base["planes"]["state.x"])
    base["planes"]["state.gone"]["reason"] = "kept"
    findings, stale = gate_skeleton_ledger(
        _entries(), ["a", "b"], _GRIDS, base
    )
    assert any(f.anchor == "state.x:reasonless" for f in findings)
    assert stale == ["state.gone"]

    base["planes"]["state.x"]["reason"] = "UNREVIEWED todo"
    findings, _ = gate_skeleton_ledger(_entries(), ["a", "b"], _GRIDS, base)
    assert any(f.anchor == "state.x:reasonless" for f in findings)


def test_write_baseline_preserves_hand_reasons_until_drift(tmp_path):
    path = str(tmp_path / "skeleton_baseline.json")
    ledger = {"audits": ["a", "b"], "grids": _GRIDS, "planes": _entries()}
    write_skeleton_baseline(path, ledger)
    base = load_skeleton_baseline(path)
    assert base["planes"]["state.x"]["reason"].strip()

    # hand-annotate, regenerate with NO drift: the annotation survives
    base_raw = json.load(open(path))
    base_raw["planes"]["state.x"]["reason"] = "hand-reviewed: fine"
    with open(path, "w") as fh:
        json.dump(base_raw, fh)
    write_skeleton_baseline(path, ledger)
    assert (
        load_skeleton_baseline(path)["planes"]["state.x"]["reason"]
        == "hand-reviewed: fine"
    )

    # a drifted entry gets the fresh machine reason, not the stale note
    drifted = {
        "audits": ["a", "b"],
        "grids": _GRIDS,
        "planes": classify_planes({
            "a": {"state.x": ((3,), "int32")},
            "b": {"state.x": ((9,), "int32")},
        }),
    }
    attach_reasons(drifted["planes"], 2)
    write_skeleton_baseline(path, drifted)
    assert (
        load_skeleton_baseline(path)["planes"]["state.x"]["reason"]
        != "hand-reviewed: fine"
    )


# ----------------------------------------------------------------------
# GL603 amplification units (stdlib arithmetic)
# ----------------------------------------------------------------------


def _amp_planes():
    entries = classify_planes({
        "a": {"state.x": ((4,), "int32"),
              "state.mine": ((100,), "int32")},
        "b": {"state.x": ((8,), "int32")},
    })
    attach_reasons(entries, 2)
    return entries


def test_grid_amplification_restricts_to_the_grid():
    planes = _amp_planes()
    both = grid_amplification(planes, ["a", "b"])
    # union: shared x at max(4,8)*4B + a's private 400B + 4B pid
    assert both["union_bytes"] == 8 * 4 + 400 + 4
    assert both["worst"] == "b"  # b's native is tiny, pays a's slot
    solo = grid_amplification(planes, ["b"])
    # a b-only grid never pays a's private plane; shared pads only to
    # the grid members' max (8)
    assert solo["union_bytes"] == 8 * 4 + 4
    assert solo["max_amplification"] < both["max_amplification"]


def test_amplification_budget_refused_by_name():
    planes = _amp_planes()
    grids = {"tight": {"audits": ("a", "b"), "max_amplification": 1.5}}
    findings, summary = amplification_findings(planes, grids)
    assert len(findings) == 1 and findings[0].rule == "GL603"
    assert findings[0].anchor == "tight" and findings[0].audit == "b"
    assert "past the declared budget 1.5x" in findings[0].message
    assert summary["tight"]["budget"] == 1.5

    # a grid naming an unledgered audit is itself a finding — a budget
    # against nothing proves nothing
    findings, _ = amplification_findings(
        planes, {"ghost": {"audits": ("a", "zz"), "max_amplification": 9}}
    )
    assert len(findings) == 1 and findings[0].anchor == "audits"
    assert "zz" in findings[0].message


# ----------------------------------------------------------------------
# pack/unpack adapters (synthetic skeleton — no tracing)
# ----------------------------------------------------------------------


def _syn_skeleton():
    entries = classify_planes({
        "a": {
            "state.pad": ((3, 2), "int32"),
            "state.cast": ((4,), "int16"),
            "state.mine": ((5,), "int8"),
            "ctx.shared": ((2,), "float32"),
        },
        "b": {
            "state.pad": ((6, 2), "int32"),
            "state.cast": ((4,), "int32"),
            "ctx.shared": ((2,), "float32"),
        },
    })
    attach_reasons(entries, 2)
    return build_skeleton(entries, audits=["a", "b"])


def _syn_state_a():
    return {
        "pad": np.arange(6, dtype=np.int32).reshape(3, 2),
        "cast": np.array([1, -2, 3, 32767], np.int16),
        "mine": np.arange(5, dtype=np.int8),
    }


def test_roundtrip_is_byte_exact_through_pad_and_cast():
    sk = _syn_skeleton()
    state = _syn_state_a()
    ctx = {"shared": np.array([1.5, -2.25], np.float32)}
    rt = unpack_state(sk, "a", pack_state(sk, "a", state))
    rt_ctx = unpack_ctx(sk, "a", pack_ctx(sk, "a", ctx))
    for name, leaf in walk_planes(state, "state").items():
        got = walk_planes(rt, "state")[name]
        assert got.dtype == leaf.dtype and got.shape == leaf.shape
        assert got.tobytes() == leaf.tobytes(), name
    assert rt_ctx["shared"].tobytes() == ctx["shared"].tobytes()


def test_packed_structure_is_identical_across_audits():
    sk = _syn_skeleton()
    pa = pack_state(sk, "a", _syn_state_a())
    pb = pack_state(sk, "b", {
        "pad": np.zeros((6, 2), np.int32),
        "cast": np.zeros((4,), np.int32),
    })

    def spec_of(packed):
        return {
            k: (tuple(v.shape), str(v.dtype))
            for k, v in walk_planes(packed, "p").items()
        }

    assert spec_of(pa) == spec_of(pb)  # the lax.switch precondition
    assert int(pa["protocol_id"]) == 0 and int(pb["protocol_id"]) == 1
    # and it matches the declared packed_spec
    want = packed_spec(sk, "state")
    assert ("pad" in want["shared"]) and ("mine" in want["priv"]["a"])
    assert want["protocol_id"] == ((), "int32")


def test_adapters_refuse_by_name():
    sk = _syn_skeleton()
    state = _syn_state_a()

    probed = dict(state, monitor_probe=np.zeros((2,), np.int32))
    with pytest.raises(SkeletonMismatchError, match="monitor_probe"):
        pack_state(sk, "a", probed)

    missing = {k: v for k, v in state.items() if k != "cast"}
    with pytest.raises(SkeletonMismatchError, match="state.cast"):
        pack_state(sk, "a", missing)

    drifted = dict(state, cast=state["cast"].astype(np.int64))
    with pytest.raises(SkeletonMismatchError, match="native spec"):
        pack_state(sk, "a", drifted)

    packed = pack_state(sk, "a", state)
    with pytest.raises(SkeletonMismatchError, match="protocol_id 0"):
        unpack_state(sk, "b", packed)
    with pytest.raises(SkeletonMismatchError, match="not in this"):
        pack_state(sk, "zz", state)


def test_walk_planes_refuses_non_dict_containers_and_dotted_keys():
    with pytest.raises(SkeletonMismatchError, match="nested dicts"):
        walk_planes({"a": [1, 2]}, "state")
    with pytest.raises(SkeletonMismatchError, match="dot-free"):
        walk_planes({"a.b": np.zeros(1)}, "state")
    leaves = walk_planes({"a": {"b": 1, "c": 2}}, "state")
    assert unflatten_planes(
        {k[len("state."):]: v for k, v in leaves.items()}
    ) == {"a": {"b": 1, "c": 2}}


def test_fingerprint_pins_the_union_spec():
    fp = skeleton_fingerprint(_syn_skeleton())
    assert fp == skeleton_fingerprint(_syn_skeleton())
    entries = classify_planes({
        "a": {"state.pad": ((3, 2), "int32")},
        "b": {"state.pad": ((7, 2), "int32")},
    })
    other = build_skeleton(entries, audits=["a", "b"])
    assert skeleton_fingerprint(other) != fp


# ----------------------------------------------------------------------
# clean-at-HEAD pins
# ----------------------------------------------------------------------


def test_skeleton_baseline_is_checked_in_and_reviewed():
    from fantoch_tpu.engine.dims import SKELETON_GRIDS

    assert os.path.exists(DEFAULT_SKELETON_BASELINE)
    base = load_skeleton_baseline()
    assert sorted(base["audits"]) == sorted(ALL_AUDITS)
    assert norm_grids(base["grids"]) == norm_grids(SKELETON_GRIDS)
    assert base["planes"], "empty unification ledger"
    for name, ent in base["planes"].items():
        assert ent["verdict"] in (SHARED, CASTABLE, PRIVATE), name
        reason = str(ent.get("reason", ""))
        assert reason.strip(), name
        assert not reason.startswith("UNREVIEWED"), name
        if ent["verdict"] in (SHARED, CASTABLE):
            assert sorted(ent["native"]) == sorted(ALL_AUDITS), name
            assert ent.get("union"), name
    # the checked-in ledger builds a valid skeleton covering both trees
    sk = build_skeleton(base["planes"], audits=base["audits"])
    names = set(base["planes"])
    assert any(n.startswith("state.") for n in names)
    assert any(n.startswith("ctx.") for n in names)
    assert specs_from_baseline(base).keys() == set(ALL_AUDITS)
    assert len(skeleton_fingerprint(sk)) == 64


def test_skeleton_waste_summary_is_jax_free():
    import subprocess
    import sys

    code = (
        "import sys\n"
        "from fantoch_tpu.lint.skeleton import skeleton_waste_summary\n"
        "s = skeleton_waste_summary()\n"
        "assert 'jax' not in sys.modules, 'jax leaked'\n"
        "import json; print(json.dumps(s))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    s = json.loads(out.stdout)
    from fantoch_tpu.engine.dims import SKELETON_GRIDS

    assert sorted(s["grids"]) == sorted(SKELETON_GRIDS)
    for gname, amp in s["grids"].items():
        assert amp["max_amplification"] <= amp["budget"], gname
        assert set(amp["audits"]) == set(
            SKELETON_GRIDS[gname]["audits"]
        )
    assert sum(s["planes"].values()) == len(
        load_skeleton_baseline()["planes"]
    )


def test_basic_skeleton_clean_at_head():
    """The fast in-tier pin: basic re-proves against the checked-in
    ledger (peers' native specs come from the baseline, so the union
    is still the full grid) with zero findings — the full 8-audit pin
    is the slow twin below + the CI skeleton-gate job."""
    findings, summary = run_skeleton(["basic"], include_partial=False)
    assert findings == [], [f.render() for f in findings]
    assert list(summary["audits"]) == ["basic"]
    assert summary["planes"]["SHARED"] > 0


@pytest.mark.slow
def test_all_audits_clean_at_head():
    findings, summary = run_skeleton()
    assert findings == [], [f.render() for f in findings]
    assert sorted(summary["audits"]) == sorted(ALL_AUDITS)
    assert summary["stale"] == []
    for gname, amp in summary["amplification"].items():
        assert amp["max_amplification"] <= amp["budget"], gname


@pytest.mark.slow
def test_roundtrip_byte_exact_full_matrix():
    """Pack/unpack every audited protocol's real state and ctx through
    the checked-in skeleton — byte-exact per plane, all eight audits
    (the GL604 alpha-equivalence leg rides in the clean-at-HEAD pin
    above; this is the raw adapter matrix)."""
    from fantoch_tpu.lint.jaxpr import TraceCache
    from fantoch_tpu.lint.shard import shard_trace

    base = load_skeleton_baseline()
    sk = build_skeleton(base["planes"], audits=base["audits"])
    cache = TraceCache()
    for audit in ALL_AUDITS:
        name, shards = (
            (audit[: -len("@2shards")], 2)
            if audit.endswith("@2shards")
            else (audit, 1)
        )
        trace = shard_trace(name, shards, cache)
        rt = unpack_state(
            sk, audit, pack_state(sk, audit, trace.state)
        )
        rt_ctx = unpack_ctx(sk, audit, pack_ctx(sk, audit, trace.ctx))
        for native, got, prefix in (
            (trace.state, rt, "state"), (trace.ctx, rt_ctx, "ctx"),
        ):
            a = walk_planes(native, prefix)
            b = walk_planes(got, prefix)
            assert sorted(a) == sorted(b), (audit, prefix)
            for pname in a:
                na, nb = np.asarray(a[pname]), np.asarray(b[pname])
                assert na.dtype == nb.dtype and na.shape == nb.shape
                assert na.tobytes() == nb.tobytes(), (audit, pname)


def test_gl604_no_regression_tempo_and_basic():
    """The tier-1 GL604 pin: tempo and basic round-trip byte-exact AND
    re-trace alpha-equivalent to the legacy step through the checked-in
    skeleton (full matrix in the slow clean-at-HEAD pin)."""
    from fantoch_tpu.lint.jaxpr import TraceCache
    from fantoch_tpu.lint.shard import shard_trace
    from fantoch_tpu.lint.skeleton import check_no_regression

    base = load_skeleton_baseline()
    sk = build_skeleton(base["planes"], audits=base["audits"])
    cache = TraceCache()
    for name in ("tempo", "basic"):
        findings = check_no_regression(shard_trace(name, 1, cache), sk)
        assert findings == [], [f.render() for f in findings]


# ----------------------------------------------------------------------
# baseline cross-pollination guard (report.py write_baseline)
# ----------------------------------------------------------------------


def test_write_baseline_refuses_gl6xx_absorption(tmp_path):
    from fantoch_tpu.lint.report import (
        LintReport, load_baseline, write_baseline,
    )

    report = LintReport()
    report.extend([
        Finding("GL001", "tempo", "a.py:f:add", "keep"),
        Finding("GL601", "skeleton", "state.ps.clock", "drop"),
        Finding("GL602", "tempo", "state.shared.pool", "drop"),
        Finding("GL603", "fpaxos", "full-grid", "drop"),
        Finding("GL604", "tempo", "step", "drop"),
    ])
    path = str(tmp_path / "baseline.json")
    write_baseline(path, report)
    assert set(load_baseline(path)) == {"GL001:tempo:a.py:f:add"}


# ----------------------------------------------------------------------
# selfchecks + CLI (slow: branch traces tempo at the audit shape)
# ----------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("kind,rule", [
    ("union", "GL601"),
    ("branch", "GL602"),
    ("pad", "GL603"),
])
def test_selfcheck_fixture_names_its_rule(kind, rule):
    findings, summary = run_skeleton_selfcheck(kind)
    assert findings, f"selfcheck {kind} is vacuously green"
    assert all(f.rule == rule for f in findings)
    assert summary["selfcheck_rule"] == rule


@pytest.mark.slow
@pytest.mark.parametrize("kind,rule", [
    ("union", "GL601"),
    ("branch", "GL602"),
    ("pad", "GL603"),
])
def test_cli_selfcheck_exits_nonzero_naming_rule(kind, rule, capsys):
    from fantoch_tpu import cli

    with pytest.raises(SystemExit) as e:
        cli.main(["lint", "--skeleton-selfcheck", kind])
    assert e.value.code == 1
    captured = capsys.readouterr()
    assert rule in captured.err
    out = json.loads(captured.out.strip().splitlines()[-1])
    assert out["selfcheck"] == kind and out["regressions"] > 0


# ----------------------------------------------------------------------
# registry / scan-set pins
# ----------------------------------------------------------------------


def test_scan_sets_cover_the_skeleton_modules():
    from fantoch_tpu.lint.rules import REPO_ROOT, expand_paths
    from fantoch_tpu.registry import (
        DETERMINISM_SCAN_PATHS,
        TRACED_SCAN_PATHS,
    )

    for paths in (TRACED_SCAN_PATHS, DETERMINISM_SCAN_PATHS):
        rels = [
            os.path.relpath(f, REPO_ROOT) for f in expand_paths(paths)
        ]
        assert "fantoch_tpu/lint/skeleton.py" in rels
        assert "fantoch_tpu/engine/skeleton.py" in rels


# ----------------------------------------------------------------------
# satellite wiring: AOT signature + checkpoint meta + scan window
# ----------------------------------------------------------------------


def test_executable_signature_skeleton_key_is_conditional():
    from fantoch_tpu.parallel.aot import executable_signature

    step_sig = {"protocol": "tempo"}
    kwargs = dict(lanes=4, window=2, donate=False, narrow=())
    legacy = executable_signature(step_sig, **kwargs)
    assert "skeleton" not in legacy  # legacy slots stay byte-identical
    marked = executable_signature(step_sig, skeleton="f" * 64, **kwargs)
    assert marked["skeleton"] == "f" * 64
    # the marker is part of the slot identity: a skeleton-packed
    # executable and a native one can never share an artifact file
    from fantoch_tpu.parallel.aot import _slot_hash

    assert _slot_hash(marked) != _slot_hash(legacy)


def test_default_scan_window_skeleton_halves_the_cap():
    from fantoch_tpu.parallel.sweep import (
        SCAN_WINDOW_MAX,
        default_scan_window,
    )

    assert default_scan_window(1) == SCAN_WINDOW_MAX
    assert default_scan_window(1, skeleton=True) == SCAN_WINDOW_MAX // 2
    # the target-steps packing rule still applies below the cap, and
    # the floor stays 1
    assert default_scan_window(1 << 14, skeleton=True) == 2
    assert default_scan_window(1 << 30, skeleton=True) == 1


def test_checkpoint_skeleton_marker_refused_by_name(tmp_path):
    from fantoch_tpu.core import Config, Planet
    from fantoch_tpu.engine import EngineDims
    from fantoch_tpu.engine.checkpoint import (
        CheckpointMismatchError,
        CheckpointSpec,
        SweepInterrupted,
    )
    from fantoch_tpu.engine.protocols import (
        dev_config_kwargs,
        dev_protocol,
    )
    from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep

    planet = Planet.new()
    regions = planet.regions()
    clients = 3
    dev = dev_protocol("basic", clients)
    total = 2 * clients
    dims = EngineDims.for_protocol(
        dev, n=3, clients=clients, payload=dev.payload_width(3),
        total_commands=total, dot_slots=total + 1, regions=3,
    )
    specs = make_sweep_specs(
        dev, planet, region_sets=[regions[:3], regions[1:4]], fs=[1],
        conflicts=[0, 100], commands_per_client=2, clients_per_region=1,
        dims=dims, config_base=Config(**dev_config_kwargs("basic", 3, 1)),
    )
    ck = str(tmp_path / "ck")
    with pytest.raises(SweepInterrupted):
        run_sweep(
            dev, dims, specs, segment_steps=8, scan_window=1,
            checkpoint=CheckpointSpec(path=ck, stop_after_segments=1),
        )
    # a native (unmarked) checkpoint must not resume into a
    # skeleton-marked runner — refusal by name, not a trace error
    with pytest.raises(CheckpointMismatchError, match="skeleton"):
        run_sweep(
            dev, dims, specs, segment_steps=8, scan_window=1,
            checkpoint=CheckpointSpec(path=ck), skeleton="cafe" * 16,
        )
    # and the unmarked resume still works (legacy artifacts unaffected)
    results = run_sweep(
        dev, dims, specs, segment_steps=8, scan_window=1,
        checkpoint=CheckpointSpec(path=ck),
    )
    assert results and not any(r.err for r in results)

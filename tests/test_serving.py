"""Open-loop serving workloads (fantoch_tpu/serving, docs/TRAFFIC.md
"Open-loop arrivals").

Contracts pinned here:

1. **Arrival schedules** — preset resolution, offered-load scaling
   (name gains ``@<load>``, gaps rescale, 1 ms floor), and the
   ``[C, T+2]`` arrival-table shape/monotonicity the engine and oracle
   both consume.
2. **Closed is free** — a lane without arrivals carries no ``ol_*``
   ctx and traces the identical step graph (GL005-style pin via the
   structure gate); an open-loop lane traces a genuinely different
   one and must never share a batch with closed lanes.
3. **Bit-exact differential** — tempo and fpaxos open-loop lanes
   (poisson + burst presets, scaled loads) under crash and drop fault
   plans run bit-exactly between the vmapped engine and the host
   oracle (latency distributions + protocol metrics).
4. **Queue delay is latency** — saturating the in-flight window
   strictly raises measured latency versus an unbounded window at the
   same arrival schedule: the arrival-queue wait lands in the curve
   (no coordinated omission).
5. **Campaign/knee wiring** — the sweep campaign's ``arrivals`` ×
   ``offered_loads`` axes journal per-(preset, load) batch groups,
   resume onto a different arrival grid is refused *by name* at both
   the campaign and checkpoint layers, and a knee sweep interrupted
   mid-grid resumes to a byte-identical ``knee.json``.
"""

import json
import os

import numpy as np
import pytest

from fantoch_tpu.client import Workload
from fantoch_tpu.client.key_gen import DeviceStream
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import (
    EngineDims,
    FaultPlan,
    LinkWindow,
    make_lane,
    run_lanes,
)
from fantoch_tpu.engine.protocols import FPaxosDev, TempoDev
from fantoch_tpu.protocol import FPaxos, Tempo
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.registry import ARRIVAL_PRESETS, arrival_preset
from fantoch_tpu.sim import Runner
from fantoch_tpu.traffic import ArrivalPhase, ArrivalSchedule, resolve_arrivals

COMMANDS = 8
CPR = 1


# ----------------------------------------------------------------------
# arrival schedules
# ----------------------------------------------------------------------


def test_arrival_presets_resolve():
    for name in ARRIVAL_PRESETS:
        sched = resolve_arrivals(name, mean_gap_ms=4, commands=20)
        if name == "closed":
            assert sched is None
            continue
        assert isinstance(sched, ArrivalSchedule)
        assert sched.name == name
        assert sum(p.commands for p in sched.phases) == 20
        if name == "burst":
            gaps = [p.mean_gap_ms for p in sched.phases]
            assert min(gaps) < gaps[0], gaps  # the spike is denser
    with pytest.raises(ValueError):
        arrival_preset("rush_hour", mean_gap_ms=4, commands=5)


def test_arrival_schedule_scale_and_table():
    sched = ArrivalSchedule(
        "poisson", (ArrivalPhase(commands=6, mean_gap_ms=8),)
    )
    double = sched.scale(200)
    assert double.name == "poisson@200"
    assert double.phases[0].mean_gap_ms == 4
    # the 1 ms floor: no offered load can produce same-instant draws
    assert sched.scale(100000).phases[0].mean_gap_ms == 1
    # load 100 keeps the bare name so legacy/simple grids stay stable
    assert sched.scale(100).name == "poisson"

    table = sched.arrival_table(seed=3, clients=4, commands=6)
    assert table.shape == (4, 8)  # [C, commands + 2]
    assert table.dtype == np.int32
    # col 0 mirrors col 1 (seqs are 1-based; slot 0 never offered) and
    # per-client arrivals are strictly increasing (>= 1 ms gaps)
    assert np.array_equal(table[:, 0], table[:, 1])
    assert (np.diff(table[:, 1:], axis=1) >= 1).all()
    # seeded: same seed reproduces, different seed diverges
    assert np.array_equal(
        table, sched.arrival_table(seed=3, clients=4, commands=6)
    )
    assert not np.array_equal(
        table, sched.arrival_table(seed=4, clients=4, commands=6)
    )
    # JSON round trip preserves value equality
    assert ArrivalSchedule.from_json(sched.to_json()) == sched

    with pytest.raises(AssertionError):
        ArrivalPhase(commands=0, mean_gap_ms=4)
    with pytest.raises(AssertionError):
        ArrivalPhase(commands=1, mean_gap_ms=0)


# ----------------------------------------------------------------------
# closed collapses to the static path; open traces differently
# ----------------------------------------------------------------------


def _tempo_setup(commands=COMMANDS, n=3):
    planet = Planet.new()
    regions = planet.regions()[:n]
    config = Config(n=n, f=1, gc_interval_ms=100,
                    tempo_detached_send_interval_ms=100)
    clients = CPR * n
    dev = TempoDev(keys=1 + clients)
    total = commands * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    return planet, regions, config, dev, dims


def test_closed_loop_collapses_to_static():
    """GL005-style pin: "closed" resolves to no schedule at all, the
    no-arrivals lane carries no ol_* ctx and traces the identical
    step graph, and an open-loop lane traces a different one."""
    from fantoch_tpu.engine.core import init_lane_state
    from fantoch_tpu.lint.gating import alpha_equivalent
    from fantoch_tpu.lint.jaxpr import trace_step

    assert resolve_arrivals("closed", mean_gap_ms=4, commands=4) is None
    assert resolve_arrivals(None, mean_gap_ms=4, commands=4) is None

    planet, regions, config, dev, dims = _tempo_setup(commands=2)

    def lane(arrivals):
        return make_lane(
            dev, planet, config, conflict_rate=100, pool_size=1,
            commands_per_client=2, clients_per_region=CPR,
            process_regions=regions, client_regions=regions, dims=dims,
            arrivals=arrivals, open_window=2,
        )

    static = lane(None)
    assert static.arrival_meta is None
    assert not any(k.startswith("ol_") for k in static.ctx)
    opened = lane("poisson")
    assert opened.arrival_meta is not None
    assert opened.ctx["ol_arrival"].shape == (dims.C, 2 + 2)
    assert int(opened.ctx["ol_window"]) == 2

    def trace(spec, name):
        state = init_lane_state(dev, dims, spec.ctx)
        return trace_step(dev, dims, state, spec.ctx, name=name)

    ok, why = alpha_equivalent(
        trace(static, "static").closed, trace(lane(None), "closed").closed
    )
    assert ok, f"the closed-loop step must not drift: {why}"
    ok, _why = alpha_equivalent(
        trace(static, "static").closed, trace(opened, "open").closed
    )
    assert not ok, "an open-loop lane must change the traced step"

    # structure-gated lanes never share a batch with closed lanes
    with pytest.raises(AssertionError):
        run_lanes(dev, dims, [lane(None), lane("poisson")])


# ----------------------------------------------------------------------
# device vs oracle bit-exact open loop under faults
# ----------------------------------------------------------------------


def _run_oracle(protocol_cls, config, regions, plan, *, arrivals,
                arrival_load=100, open_window, seed=0,
                commands=COMMANDS):
    planet = Planet.new()
    workload = Workload(
        shard_count=1,
        key_gen=DeviceStream(conflict_rate=100, pool_size=1, seed=seed),
        keys_per_command=1,
        commands_per_client=commands,
        payload_size=0,
    )
    runner = Runner(
        protocol_cls, planet, config, workload, CPR, regions,
        list(regions), seed=seed, fault_plan=plan,
        arrivals=arrivals, arrival_load=arrival_load,
        open_window=open_window,
    )
    metrics, _, latencies = runner.run(extra_sim_time_ms=1000)
    fast = slow = stable = 0
    for pm, _em in metrics.values():
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
        stable += pm.get_aggregated(ProtocolMetricsKind.STABLE) or 0
    return latencies, fast, slow, stable


def _assert_latencies_equal(res, oracle_lat, regions):
    for region in regions:
        dev_done = res.issued(region)
        if region not in oracle_lat:
            assert dev_done == 0, region
            continue
        _issued, hist = oracle_lat[region]
        assert dev_done == hist.count(), region
        if hist.count():
            assert res.latency_mean(region) == hist.mean(), region
            assert res.histogram(region).mean() == hist.mean(), region


def test_engine_oracle_bitexact_openloop_faults_tempo():
    """Tempo, burst arrivals + crash + link window, in-flight cap 3:
    engine ≡ oracle (queue-delay-inclusive latencies + metrics)."""
    n, seed = 3, 0
    planet = Planet.new()
    regions = planet.regions()[:n]
    config = Config(n=n, f=1, gc_interval_ms=100,
                    tempo_detached_send_interval_ms=100)
    plan = FaultPlan(
        crashes={2: 260},
        windows=(LinkWindow(src=0, dst=1, t0=40, t1=220, mult=3),),
    )
    clients = CPR * n
    dev = TempoDev(keys=1 + clients)
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    spec = make_lane(
        dev, planet, config, conflict_rate=100, pool_size=1,
        commands_per_client=COMMANDS, clients_per_region=CPR,
        process_regions=regions, client_regions=regions, dims=dims,
        seed=seed, faults=plan, arrivals="burst", open_window=3,
    )
    res = run_lanes(dev, dims, [spec])[0]
    assert not res.err, res.err_cause
    oracle_lat, fast, slow, stable = _run_oracle(
        Tempo, config, regions, plan, arrivals="burst", open_window=3,
        seed=seed,
    )
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    assert int(res.protocol_metrics["stable"].sum()) == stable
    _assert_latencies_equal(res, oracle_lat, regions)


def test_engine_oracle_bitexact_openloop_drops_tempo():
    """Tempo, poisson arrivals scaled to 200% load under seeded wire
    drops (horizon-bounded): engine ≡ oracle — wire faults never touch
    the client hops carrying staged arrivals."""
    n, seed = 3, 2
    planet = Planet.new()
    regions = planet.regions()[:n]
    config = Config(n=n, f=1, gc_interval_ms=100,
                    tempo_detached_send_interval_ms=100)
    plan = FaultPlan(drop_bp=500, drop_seed=9, horizon_ms=5000)
    clients = CPR * n
    dev = TempoDev(keys=1 + clients)
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    spec = make_lane(
        dev, planet, config, conflict_rate=100, pool_size=1,
        commands_per_client=COMMANDS, clients_per_region=CPR,
        process_regions=regions, client_regions=regions, dims=dims,
        seed=seed, faults=plan, arrivals="poisson", arrival_load=200,
        open_window=2,
    )
    res = run_lanes(dev, dims, [spec])[0]
    assert not res.err, res.err_cause
    oracle_lat, fast, slow, stable = _run_oracle(
        Tempo, config, regions, plan, arrivals="poisson",
        arrival_load=200, open_window=2, seed=seed,
    )
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    assert int(res.protocol_metrics["stable"].sum()) == stable
    _assert_latencies_equal(res, oracle_lat, regions)


def test_engine_oracle_bitexact_openloop_faults_fpaxos():
    """FPaxos (leader-based), burst arrivals + non-leader crash +
    window: engine ≡ oracle."""
    n, seed = 3, 1
    planet = Planet.new()
    regions = planet.regions()[:n]
    config = Config(n=n, f=1, gc_interval_ms=100, leader=1)
    plan = FaultPlan(
        crashes={2: 300},
        windows=(LinkWindow(src=1, dst=0, t0=0, t1=150, mult=2),),
    )
    clients = CPR * n
    dev = FPaxosDev
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    spec = make_lane(
        dev, planet, config, conflict_rate=100, pool_size=1,
        commands_per_client=COMMANDS, clients_per_region=CPR,
        process_regions=regions, client_regions=regions, dims=dims,
        seed=seed, faults=plan, arrivals="burst", open_window=3,
    )
    res = run_lanes(dev, dims, [spec])[0]
    assert not res.err, res.err_cause
    oracle_lat, _fast, _slow, stable = _run_oracle(
        FPaxos, config, regions, plan, arrivals="burst", open_window=3,
        seed=seed,
    )
    assert int(res.protocol_metrics["stable"].sum()) == stable
    _assert_latencies_equal(res, oracle_lat, regions)


def test_engine_oracle_bitexact_openloop_drops_fpaxos():
    """FPaxos, ramp arrivals at 150% load under seeded drops."""
    n, seed = 3, 4
    planet = Planet.new()
    regions = planet.regions()[:n]
    config = Config(n=n, f=1, gc_interval_ms=100, leader=1)
    plan = FaultPlan(drop_bp=400, drop_seed=5, horizon_ms=5000)
    clients = CPR * n
    dev = FPaxosDev
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    spec = make_lane(
        dev, planet, config, conflict_rate=100, pool_size=1,
        commands_per_client=COMMANDS, clients_per_region=CPR,
        process_regions=regions, client_regions=regions, dims=dims,
        seed=seed, faults=plan, arrivals="ramp", arrival_load=150,
        open_window=4,
    )
    res = run_lanes(dev, dims, [spec])[0]
    assert not res.err, res.err_cause
    oracle_lat, _fast, _slow, stable = _run_oracle(
        FPaxos, config, regions, plan, arrivals="ramp",
        arrival_load=150, open_window=4, seed=seed,
    )
    assert int(res.protocol_metrics["stable"].sum()) == stable
    _assert_latencies_equal(res, oracle_lat, regions)


# ----------------------------------------------------------------------
# the in-flight cap pins queue delay into latency
# ----------------------------------------------------------------------


def test_open_window_saturation_counts_queue_delay():
    """At a saturating offered load, a window-1 lane's latency must
    strictly exceed an unbounded-window lane's on the same arrival
    schedule: the excess is exactly the arrival-queue wait, which an
    open loop counts (coordinated omission would hide it)."""
    planet, regions, config, dev, dims = _tempo_setup()

    def lane(window):
        return make_lane(
            dev, planet, config, conflict_rate=100, pool_size=1,
            commands_per_client=COMMANDS, clients_per_region=CPR,
            process_regions=regions, client_regions=regions, dims=dims,
            seed=0, arrivals="poisson", arrival_load=400,
            arrival_gap_ms=4, open_window=window,
        )

    capped, uncapped = run_lanes(
        dev, dims, [lane(1)]
    )[0], run_lanes(dev, dims, [lane(COMMANDS)])[0]
    assert not capped.err and not uncapped.err
    means = []
    for res in (capped, uncapped):
        total = count = 0.0
        for region in regions:
            h = res.histogram(region)
            total += h.mean() * h.count()
            count += h.count()
        assert count == COMMANDS * len(regions) * CPR
        means.append(total / count)
    assert means[0] > means[1], (
        "a saturated in-flight window must surface queue delay in "
        f"latency (capped {means[0]:.1f} ms <= uncapped {means[1]:.1f} ms)"
    )


# ----------------------------------------------------------------------
# campaign arrivals axis + refusal by name
# ----------------------------------------------------------------------


def test_campaign_arrivals_axis_and_refusals(tmp_path):
    from fantoch_tpu.campaign import (
        CampaignError,
        campaign_from_json,
        run_campaign,
    )

    grid = {
        "kind": "sweep",
        "protocols": ["basic"],
        "ns": [3],
        "conflicts": [100],
        "subsets": 1,
        "commands_per_client": 2,
        "batch_lanes": 2,
        "segment_steps": 64,
        "arrivals": ["poisson"],
        "offered_loads": [100, 200],
        "open_window": 2,
    }
    spec = campaign_from_json(grid)
    path = str(tmp_path / "c1")
    summary = run_campaign(path, spec)
    assert summary["done"], summary
    assert summary["errors"] == 0
    # per-(preset, load) batch groups journaled under tagged ids
    ids = set()
    with open(os.path.join(path, "journal.jsonl")) as fh:
        for line in fh:
            ids.add(json.loads(line)["id"])
    assert any("/apoissonl100/" in i for i in ids), ids
    assert any("/apoissonl200/" in i for i in ids), ids

    # resume onto a different arrival grid: refused by the stored-spec
    # equality check, by name
    other = campaign_from_json({**grid, "arrivals": ["burst"]})
    with pytest.raises(CampaignError):
        run_campaign(path, other)

    # unknown preset / empty axis / bad loads refused at parse time
    with pytest.raises(CampaignError, match="arrival preset"):
        campaign_from_json({**grid, "arrivals": ["rush_hour"]})
    with pytest.raises(CampaignError, match="offered_loads"):
        campaign_from_json({**grid, "offered_loads": [0]})
    with pytest.raises(CampaignError, match="think delays"):
        campaign_from_json({**grid, "traffic": ["diurnal"]})

    # closed grids keep the legacy (untagged) batch ids
    closed = campaign_from_json(
        {k: v for k, v in grid.items()
         if k not in ("arrivals", "offered_loads", "open_window")}
    )
    path2 = str(tmp_path / "c2")
    assert run_campaign(path2, closed)["done"]
    with open(os.path.join(path2, "journal.jsonl")) as fh:
        for line in fh:
            assert "/a" not in json.loads(line)["id"].split("/b")[0]


def test_checkpoint_refuses_arrival_swap(tmp_path):
    """The sweep checkpoint names its arrival schedule: resuming burst
    lanes onto a poisson checkpoint raises CheckpointMismatchError
    naming `arrivals`; a pre-arrivals manifest (no key) still resumes
    a closed-loop run."""
    from fantoch_tpu.engine.checkpoint import (
        CheckpointMismatchError,
        CheckpointSpec,
        SweepInterrupted,
    )
    from fantoch_tpu.engine.protocols import BasicDev
    from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep

    planet = Planet.new()
    regions = planet.regions()[:3]
    commands = 2
    clients = 3
    total = commands * clients
    dev = BasicDev
    dims = EngineDims.for_protocol(
        dev, n=3, clients=clients, payload=dev.payload_width(3),
        total_commands=total, dot_slots=total + 1, regions=3,
    )

    def specs(arrivals):
        return make_sweep_specs(
            dev, planet, region_sets=[regions], fs=[1], conflicts=[100],
            commands_per_client=commands, clients_per_region=1,
            dims=dims, arrivals=arrivals, open_window=2,
        )

    ck = str(tmp_path / "ck")
    with pytest.raises(SweepInterrupted):
        run_sweep(
            dev, dims, specs("poisson"), segment_steps=8, scan_window=1,
            checkpoint=CheckpointSpec(
                path=ck, keep=True, stop_after_segments=1
            ),
        )
    with pytest.raises(CheckpointMismatchError, match="arrivals"):
        run_sweep(
            dev, dims, specs("burst"), segment_steps=8,
            checkpoint=CheckpointSpec(path=ck, keep=True),
        )
    results = run_sweep(
        dev, dims, specs("poisson"), segment_steps=8,
        checkpoint=CheckpointSpec(path=ck),
    )
    assert len(results) == 1 and not results[0].err

    # legacy compatibility: a pre-arrivals manifest must still resume
    # a closed-loop run (the by-name check only applies to open lanes)
    ck2 = str(tmp_path / "ck_legacy")
    with pytest.raises(SweepInterrupted):
        run_sweep(
            dev, dims, specs(None), segment_steps=8, scan_window=1,
            checkpoint=CheckpointSpec(
                path=ck2, keep=True, stop_after_segments=1
            ),
        )
    mpath = os.path.join(ck2, "manifest.json")
    manifest = json.load(open(mpath))
    assert manifest["meta"].pop("arrivals") == ["closed"]
    with open(mpath, "w") as fh:
        json.dump(manifest, fh)
    results = run_sweep(
        dev, dims, specs(None), segment_steps=8,
        checkpoint=CheckpointSpec(path=ck2),
    )
    assert len(results) == 1 and not results[0].err


# ----------------------------------------------------------------------
# knee location + artifact gate (host-only)
# ----------------------------------------------------------------------


def test_locate_knee():
    from fantoch_tpu.serving import locate_knee

    curve = {
        "50": {"p99": 100.0}, "100": {"p99": 150.0},
        "200": {"p99": 350.0}, "400": {"p99": 900.0},
    }
    assert locate_knee(curve, 3.0) == 200
    assert locate_knee(curve, 2.0) == 200
    assert locate_knee(curve, 10.0) is None
    # an errored baseline locates nothing (no envelope to leave)
    assert locate_knee({"50": {"p99": None}, "100": {"p99": 9.0}}) is None
    # errored mid-points are skipped, not treated as exceedances
    assert locate_knee(
        {"50": {"p99": 10.0}, "100": {"p99": None},
         "200": {"p99": 99.0}}, 3.0
    ) == 200


def test_knee_artifact_gate(tmp_path):
    from fantoch_tpu.serving import check_knee_artifact, run_knee_sweep

    artifact, summary = run_knee_sweep(
        str(tmp_path / "dry"), protocols=("tempo", "fpaxos"),
        loads=(50, 200), dryrun=True,
    )
    assert summary["done"] and summary["dryrun"]
    check_knee_artifact(artifact)
    on_disk = json.load(open(summary["artifact"]))
    check_knee_artifact(on_disk)
    assert on_disk["points"] is None

    base = json.loads(json.dumps(artifact))
    base["dryrun"] = False
    stats = {"mean": 1.0, "p50": 1.0, "p99": 1.0, "count": 4,
             "goodput_cps": 10.0, "lanes": 1, "errors": 0}

    def point(proto, curve, knee):
        return {"regions": ["a", "b", "c"], "protocol": proto,
                "curve": curve, "knee": knee}

    good = dict(base, points=[
        point(p, {"50": dict(stats), "200": dict(stats)}, None)
        for p in ("tempo", "fpaxos")
    ])
    check_knee_artifact(good)
    # errored points carry nulls + a cause, never fake percentiles
    err_stats = {"mean": None, "p50": None, "p99": None, "count": 0,
                 "goodput_cps": None, "lanes": 1, "errors": 1,
                 "error_cause": "pool-overflow"}
    check_knee_artifact(dict(base, points=[
        point(p, {"50": dict(stats), "200": dict(err_stats)}, None)
        for p in ("tempo", "fpaxos")
    ]))
    fake = dict(err_stats, p99=0.0, error_cause=None)
    with pytest.raises(AssertionError):
        check_knee_artifact(dict(base, points=[
            point(p, {"50": dict(stats), "200": dict(fake)}, None)
            for p in ("tempo", "fpaxos")
        ]))
    # a knee outside the swept ladder is refused
    with pytest.raises(AssertionError):
        check_knee_artifact(dict(good, points=[
            dict(good["points"][0], knee=75), good["points"][1]
        ]))
    # every swept protocol must be represented
    with pytest.raises(AssertionError):
        check_knee_artifact(dict(good, points=good["points"][:1]))
    # a curve missing a swept load is refused
    with pytest.raises(AssertionError):
        check_knee_artifact(dict(base, points=[
            point(p, {"50": dict(stats)}, None)
            for p in ("tempo", "fpaxos")
        ]))


def test_frontier_artifact_gate_rank_by_knee():
    from fantoch_tpu.bote.validate import (
        check_frontier_artifact,
        frontier_candidates,
        validate_frontier,
    )

    planet = Planet.new()
    cands = frontier_candidates(planet, 3, 2)
    artifact, summary = validate_frontier(
        "/nonexistent-never-written", planet=planet, candidates=cands,
        rank_by="knee", loads=(50, 200), dryrun=True,
        out=os.devnull,
    )
    assert summary["done"] and summary["dryrun"]
    check_frontier_artifact(artifact)
    assert artifact["rank_by"] == "knee"
    assert artifact["serving"]["loads"] == [50, 200]
    # score-ranked artifacts must not smuggle serving parameters
    bad = json.loads(json.dumps(artifact))
    bad["rank_by"] = "score"
    with pytest.raises(AssertionError):
        check_frontier_artifact(bad)
    # knee-ranked measured candidates need a curve per protocol/load
    measured = json.loads(json.dumps(artifact))
    measured["dryrun"] = False
    stats = {"mean": 1.0, "p50": 1.0, "p99": 1.0, "count": 2,
             "goodput_cps": 5.0, "lanes": 1, "errors": 0}
    for cand in measured["candidates"]:
        cand["measured"] = {
            p: {"50": dict(stats), "200": dict(stats)}
            for p in measured["protocols"]
        }
        cand["knee"] = {p: 200 for p in measured["protocols"]}
    check_frontier_artifact(measured)
    measured["candidates"][0]["knee"] = {
        p: 75 for p in measured["protocols"]
    }
    with pytest.raises(AssertionError):
        check_frontier_artifact(measured)


# ----------------------------------------------------------------------
# knee sweep through the campaign manager (slow tier)
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_knee_sweep_interrupted_resume_byte_identical(tmp_path):
    """A knee sweep stopped mid-grid (budget stop — the same journal
    state a SIGKILL leaves) resumes to a knee.json byte-identical to
    the uninterrupted control's."""
    from fantoch_tpu.serving import check_knee_artifact, run_knee_sweep

    kw = dict(
        protocols=("tempo",), ns=(3,), arrival="poisson",
        loads=(50, 200), commands_per_client=6, open_window=2,
        segment_steps=512,
    )
    ctrl = str(tmp_path / "ctrl")
    art_ctrl, summary = run_knee_sweep(ctrl, **kw)
    assert summary["done"], summary
    check_knee_artifact(art_ctrl)

    intr = str(tmp_path / "intr")
    art0, s0 = run_knee_sweep(intr, budget_s=0.0, **kw)
    assert art0 is None and not s0["done"]
    art1, s1 = run_knee_sweep(intr, resume=True, **kw)
    assert s1["done"], s1
    with open(os.path.join(ctrl, "knee.json"), "rb") as fh:
        ctrl_bytes = fh.read()
    with open(os.path.join(intr, "knee.json"), "rb") as fh:
        intr_bytes = fh.read()
    assert ctrl_bytes == intr_bytes


@pytest.mark.slow
def test_knee_sweep_locates_knee_two_protocols(tmp_path):
    """The measured curve artifact locates a knee for both protocols
    on the CPU mesh: the load-25 baseline is unloaded, the heavy loads
    saturate the in-flight window, and queue delay drives p99 past
    knee_mult x baseline."""
    from fantoch_tpu.serving import check_knee_artifact, run_knee_sweep

    artifact, summary = run_knee_sweep(
        str(tmp_path / "knee"), protocols=("tempo", "fpaxos"),
        ns=(3,), arrival="poisson", loads=(25, 400, 3200),
        commands_per_client=48, open_window=4, segment_steps=1024,
    )
    assert summary["done"], summary
    check_knee_artifact(artifact)
    assert {p["protocol"] for p in artifact["points"]} == {
        "tempo", "fpaxos"
    }
    for point in artifact["points"]:
        assert point["knee"] == 400, point
        curve = point["curve"]
        assert curve["3200"]["p99"] > 3.0 * curve["25"]["p99"]
        # goodput keeps rising with offered load until saturation
        assert curve["400"]["goodput_cps"] > curve["25"]["goodput_cps"]

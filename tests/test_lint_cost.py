"""graft-cost tests (fantoch_tpu/lint/cost.py + lanes.py): kernel
ledger units on synthetic jaxprs, the GL201 regression gate, the GL202
fused-footprint gate, GL203 lane-taint units (cross-lane reductions,
rolls, sorts and gathers must flag; vmap-built graphs must prove
clean), the sweep driver's verified lane-sharding path, and the seeded
CI self-checks."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fantoch_tpu.lint.cost import (
    DEFAULT_COST_BASELINE,
    CostLedger,
    build_ledger,
    classify,
    cost_findings,
    load_cost_baseline,
)
from fantoch_tpu.lint.lanes import TAINT_LANES, taint_closed
from fantoch_tpu.registry import DEV_PROTOCOLS

I32 = jnp.int32


# ----------------------------------------------------------------------
# GL201: kernel classification + ledger
# ----------------------------------------------------------------------


def test_classify_kernel_classes():
    assert classify("add") == "fused"
    assert classify("broadcast_in_dim") == "fused"
    assert classify("scatter") == "scatter"
    assert classify("dynamic_update_slice") == "scatter"
    assert classify("gather") == "gather"
    assert classify("reduce_sum") == "reduce"
    assert classify("dot_general") == "matmul"
    assert classify("sort") == "sort"
    # unknown primitives count as boundaries (conservative for a
    # regression gate), never silently as fused
    assert classify("some_new_primitive") == "other"


def _ledger(f, *args) -> CostLedger:
    return build_ledger(jax.make_jaxpr(f)(*args), "syn")


def test_ledger_counts_boundaries_and_fusions():
    def f(x, i):
        y = x * 2 + 1                      # fused chain
        y = y.at[i].set(0)                 # scatter kernel
        return jnp.sum(y)                  # reduce kernel

    led = _ledger(f, np.zeros((8,), np.int32), np.int32(1))
    assert led.boundaries.get("scatter") == 1
    assert led.boundaries.get("reduce") == 1
    assert led.fusion_groups >= 1
    assert led.kernels == (
        sum(led.boundaries.values()) + led.fusion_groups
    )
    lo, hi = led.est_ms
    assert 0 < lo < hi


def test_ledger_scan_body_multiplies_by_trips():
    trips = 7

    def body(c, _):
        return c.at[c[0] % 4].add(1), None  # one scatter per iteration

    def f(x):
        out, _ = jax.lax.scan(body, x, None, length=trips)
        return out

    led = _ledger(f, np.zeros((4,), np.int32))

    def one(c, _):
        return c.at[c[0] % 4].add(1), None

    led1 = build_ledger(
        jax.make_jaxpr(
            lambda x: jax.lax.scan(one, x, None, length=1)[0]
        )(np.zeros((4,), np.int32)),
        "syn1",
    )
    # trips x the per-iteration kernels (docs/PERF.md: a loop body pays
    # the per-kernel overhead every iteration)
    assert led.kernels - led1.kernels >= (trips - 1) * 1


def test_gl201_regression_gate():
    led = CostLedger(
        audit="tempo", kernels=100, fusion_groups=10,
        boundaries={"scatter": 90}, est_ms=(10.0, 30.0), groups=[],
    )
    baseline = {"kernels": {"tempo": 100}}
    assert cost_findings(led, baseline) == []
    baseline = {"kernels": {"tempo": 99}}
    fs = cost_findings(led, baseline)
    assert [f.rule for f in fs] == ["GL201"], fs
    assert "regressed" in fs[0].message
    # a protocol missing from the baseline is itself a finding (a new
    # protocol must be consciously added to the cost gate)
    fs = cost_findings(led, {"kernels": {}})
    assert [f.rule for f in fs] == ["GL201"] and "no cost-baseline" in (
        fs[0].message
    )


# ----------------------------------------------------------------------
# GL202: fused-group footprint
# ----------------------------------------------------------------------


def test_gl202_flags_oversized_fused_group():
    # a fused broadcast chain whose intermediate is ~4 MiB: over a
    # 2 MiB budget, fine under 16 MiB
    def f(x):
        big = x[:, None] * jnp.ones((1, 1024), I32)  # [1024, 1024] i32
        return jnp.max(big * 2 + 1)

    closed = jax.make_jaxpr(f)(np.zeros((1024,), np.int32))
    led = build_ledger(closed, "syn")
    over = cost_findings(led, None, vmem_budget_mib=2)
    assert any(g.rule == "GL202" for g in over), over
    assert "MiB" in over[0].message
    assert cost_findings(led, None, vmem_budget_mib=16) == []


def test_gl202_budget_from_baseline_headroom():
    led = build_ledger(
        jax.make_jaxpr(
            lambda x: jnp.max(x[:, None] * jnp.ones((1, 1024), I32))
        )(np.zeros((1024,), np.int32)),
        "syn",
    )
    peak_mib = max(g.peak_bytes for g in led.groups) / 2**20
    tight = {"vmem_peak_mib": {"syn": peak_mib / 2}, "vmem_headroom": 1.25}
    assert any(
        f.rule == "GL202" for f in cost_findings(led, tight)
    )
    loose = {"vmem_peak_mib": {"syn": peak_mib}, "vmem_headroom": 1.25}
    assert not any(
        f.rule == "GL202" for f in cost_findings(led, loose)
    )


def test_cost_baseline_covers_every_device_protocol():
    base = load_cost_baseline(DEFAULT_COST_BASELINE)
    assert set(DEV_PROTOCOLS) <= set(base["kernels"]), base["kernels"]
    assert set(DEV_PROTOCOLS) <= set(base["vmem_peak_mib"])
    assert base["lanes"] == 512
    assert base["vmem_headroom"] > 1.0


# ----------------------------------------------------------------------
# GL203: lane-taint units
# ----------------------------------------------------------------------

B = 64


def _taint(f, *shapes):
    args = [jax.ShapeDtypeStruct((B,) + s, np.int32) for s in shapes]
    return taint_closed(jax.make_jaxpr(f)(*args), "syn", B)


def test_taint_flags_cross_lane_reduction():
    fs = _taint(
        lambda x: x - jnp.sum(x, axis=0, keepdims=True) // B, (4,)
    )
    assert any(":reduce_sum" in g.anchor for g in fs), fs


def test_taint_flags_lane_roll_and_sort():
    assert _taint(lambda x: jnp.roll(x, 1, axis=0), (4,))
    assert _taint(lambda x: jnp.sort(x, axis=0), (4,))


def test_taint_flags_cross_lane_gather():
    def f(x, i):
        return x[(i[:, 0] + 1) % B]  # lane i reads lane i+1's row

    assert _taint(f, (4,), (1,))


def test_taint_clean_on_vmapped_step_shapes():
    # per-lane elementwise + per-lane reductions + a vmapped scan (the
    # carry starts lane-constant and picks the lane axis up — the
    # fixpoint must converge instead of flagging)
    def lane(x):
        def body(c, v):
            return c + v, c * 2

        tot, ys = jax.lax.scan(body, jnp.int32(0), x * 2 + 1)
        return tot + jnp.max(x), ys

    args = [jax.ShapeDtypeStruct((B, 8), np.int32)]
    closed = jax.make_jaxpr(jax.vmap(lane))(*args)
    assert taint_closed(closed, "syn", B) == []


def test_taint_clean_on_vmapped_scatter_gather():
    def lane(tbl, i):
        row = tbl[i % 4]                     # per-lane gather
        return tbl.at[i % 4].set(row * 2)    # per-lane scatter

    args = [
        jax.ShapeDtypeStruct((B, 4, 3), np.int32),
        jax.ShapeDtypeStruct((B,), np.int32),
    ]
    closed = jax.make_jaxpr(jax.vmap(lane))(*args)
    assert taint_closed(closed, "syn", B) == []


def test_lanes_prove_basic_protocol_clean():
    """One real protocol's step proves lane-independent in tier-1 (the
    full grid is the CI cost-gate job)."""
    from fantoch_tpu.lint.jaxpr import build_protocol_trace
    from fantoch_tpu.lint.lanes import check_lanes

    trace = build_protocol_trace("basic")
    assert check_lanes(trace) == []


# ----------------------------------------------------------------------
# the verified lane-sharding path (parallel/sweep.py)
# ----------------------------------------------------------------------


def test_run_sweep_shard_lanes_proves_once(monkeypatch):
    from fantoch_tpu.parallel import sweep as sweep_mod

    calls = []

    def fake_prove(protocol, dims, state, ctx, **kw):
        calls.append(kw)
        return []

    monkeypatch.setattr(
        "fantoch_tpu.lint.lanes.prove_step_lane_independent", fake_prove
    )
    sweep_mod._LANE_PROOFS.clear()
    from fantoch_tpu.core import Config, Planet
    from fantoch_tpu.engine import EngineDims
    from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol
    from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep

    planet = Planet.new()
    dev = dev_protocol("basic", 3)
    dims = EngineDims.for_protocol(
        dev, n=3, clients=3, payload=dev.payload_width(3),
        total_commands=6, dot_slots=7, regions=3,
    )
    specs = make_sweep_specs(
        dev, planet, region_sets=[planet.regions()[:3]], fs=[1],
        conflicts=[100], commands_per_client=2, clients_per_region=1,
        dims=dims, config_base=Config(**dev_config_kwargs("basic", 3, 1)),
    )
    try:
        run_sweep(dev, dims, specs, shard_lanes=True)
        run_sweep(dev, dims, specs, shard_lanes=True)
        assert len(calls) == 1, "the proof must be cached per protocol"
    finally:
        # the fake proof must not leak into tests that exercise the
        # real prover on the same (protocol, dims) key
        sweep_mod._LANE_PROOFS.clear()


def test_run_sweep_shard_lanes_refuses_mixing(monkeypatch):
    from fantoch_tpu.lint.report import Finding
    from fantoch_tpu.parallel import sweep as sweep_mod
    from fantoch_tpu.parallel.sweep import LaneMixingError

    monkeypatch.setattr(
        "fantoch_tpu.lint.lanes.prove_step_lane_independent",
        lambda *a, **k: [
            Finding("GL203", "syn", "x:y:reduce_sum", "cross-lane")
        ],
    )
    sweep_mod._LANE_PROOFS.clear()
    from fantoch_tpu.core import Config, Planet
    from fantoch_tpu.engine import EngineDims
    from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol
    from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep

    planet = Planet.new()
    dev = dev_protocol("basic", 3)
    dims = EngineDims.for_protocol(
        dev, n=3, clients=3, payload=dev.payload_width(3),
        total_commands=6, dot_slots=7, regions=3,
    )
    specs = make_sweep_specs(
        dev, planet, region_sets=[planet.regions()[:3]], fs=[1],
        conflicts=[100], commands_per_client=2, clients_per_region=1,
        dims=dims, config_base=Config(**dev_config_kwargs("basic", 3, 1)),
    )
    with pytest.raises(LaneMixingError, match="GL203"):
        run_sweep(dev, dims, specs, shard_lanes=True)
    sweep_mod._LANE_PROOFS.clear()


# ----------------------------------------------------------------------
# seeded CI self-checks (slow: each traces tempo at the sweep shape)
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_cost_selfcheck_scatter_regresses_gl201():
    from fantoch_tpu.lint.cost import run_cost_selfcheck

    fs = run_cost_selfcheck("scatter")
    assert any(f.rule == "GL201" for f in fs), fs


@pytest.mark.slow
def test_cost_selfcheck_vmem_trips_gl202():
    from fantoch_tpu.lint.cost import run_cost_selfcheck

    fs = run_cost_selfcheck("vmem")
    assert any(f.rule == "GL202" for f in fs), fs


@pytest.mark.slow
def test_cost_head_within_baseline():
    """The checked-in cost baseline matches HEAD (regenerate with
    `lint --cost --write-cost-baseline` after a reviewed change)."""
    from fantoch_tpu.lint.cost import run_cost

    findings, summary = run_cost(DEV_PROTOCOLS)
    assert findings == [], [f.render() for f in findings]
    base = load_cost_baseline(DEFAULT_COST_BASELINE)
    for name in DEV_PROTOCOLS:
        assert summary[name]["kernels"] <= base["kernels"][name]


def test_cli_rejects_unknown_selfcheck(capsys):
    """argparse owns the --cost-selfcheck vocabulary (the CI job only
    ever passes scatter/vmem; the real runs are the cost-gate job)."""
    import contextlib
    import io

    from fantoch_tpu import cli

    with contextlib.redirect_stderr(io.StringIO()):
        with pytest.raises(SystemExit) as e:
            cli.main(["lint", "--cost-selfcheck", "bogus"])
    assert e.value.code == 2  # argparse usage error

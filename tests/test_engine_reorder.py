"""Device-engine message reordering (runner.rs:520-524 analog).

Every hop's delay scales by a uniform [0, 10) draw, so deliveries race
and interleave far more aggressively than WAN geometry allows — the
race-hunting perturbation the reference's sim tests always enable
(fantoch_ps/src/protocol/mod.rs:660, ``runner.reorder_messages``).
Randomized delays void the conservative-lookahead bound (lanes run
serialized) and make tie order engine-defined, so these tests assert
the protocol invariants the reference's ``sim_test`` checks
(mod.rs:116-167): every command commits, fast/slow totals account for
every commit, and GC reaches every process.
"""

import pytest

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.protocols import AtlasDev, CaesarDev, TempoDev

COMMANDS = 20
CPR = 1


def run_reordered(dev_cls, config, conflict, seed, **dev_kw):
    n = config.n
    planet = Planet.new()
    regions = planet.regions()[:n]
    clients = CPR * n
    if dev_cls is TempoDev:
        dev = TempoDev.for_load(keys=1 + clients, clients=clients)
    else:
        dev = dev_cls(keys=1 + clients, **dev_kw)
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev,
        n=n,
        clients=clients,
        payload=dev.payload_width(n),
        total_commands=total,
        dot_slots=total + 1,
        regions=n,
    )
    spec = make_lane(
        dev,
        planet,
        config,
        conflict_rate=conflict,
        pool_size=1,
        commands_per_client=COMMANDS,
        clients_per_region=CPR,
        process_regions=regions,
        client_regions=regions,
        dims=dims,
        # delays scale by U(0, 10): the final MCommitDot -> frontier ->
        # MGC exchange after the last completion can take several
        # seconds, so give GC the same post-completion window the
        # oracle harness uses (extra_sim_time 10 s, scaled for x10)
        extra_time_ms=30_000,
        seed=seed,
        reorder=True,
    )
    return run_lanes(dev, dims, [spec])[0], total


@pytest.mark.parametrize("seed", [0, 1])
def test_tempo_reorder_invariants(seed):
    config = Config(
        n=3, f=1, gc_interval_ms=100, tempo_detached_send_interval_ms=100
    )
    res, total = run_reordered(TempoDev, config, 100, seed)
    assert res.err == 0, res.err_cause
    fast = int(res.protocol_metrics["fast_path"].sum())
    slow = int(res.protocol_metrics["slow_path"].sum())
    assert fast + slow == total
    assert int(res.protocol_metrics["stable"].sum()) == config.n * total
    assert res.completed == total


@pytest.mark.parametrize("seed", [0, 2])
def test_atlas_reorder_invariants(seed):
    config = Config(n=3, f=1, gc_interval_ms=100)
    res, total = run_reordered(AtlasDev, config, 100, seed=seed)
    assert res.err == 0, res.err_cause
    fast = int(res.protocol_metrics["fast_path"].sum())
    slow = int(res.protocol_metrics["slow_path"].sum())
    assert fast + slow == total
    assert int(res.protocol_metrics["stable"].sum()) == config.n * total
    assert res.completed == total


@pytest.mark.parametrize("seed", [0, 2])
def test_caesar_reorder_invariants(seed):
    config = Config(
        n=5, f=2, gc_interval_ms=100, caesar_wait_condition=True
    )
    res, total = run_reordered(CaesarDev, config, 100, seed=seed)
    assert res.err == 0, res.err_cause
    fast = int(res.protocol_metrics["fast_path"].sum())
    slow = int(res.protocol_metrics["slow_path"].sum())
    assert fast + slow == total
    assert int(res.protocol_metrics["stable"].sum()) == config.n * total
    assert res.completed == total

"""Device-engine message reordering (runner.rs:520-524 analog).

Every hop's delay scales by a uniform [0, 10) draw, so deliveries race
and interleave far more aggressively than WAN geometry allows — the
race-hunting perturbation the reference's sim tests always enable
(fantoch_ps/src/protocol/mod.rs:660, ``runner.reorder_messages``).
Randomized delays void the conservative-lookahead bound (lanes run
serialized) and make tie order engine-defined, so these tests assert
the protocol invariants the reference's ``sim_test`` checks
(mod.rs:116-167): every command commits, fast/slow totals account for
every commit, and GC reaches every process.

Coverage matrix (a round-4 gap: EPaxos and FPaxos device twins had no
reorder coverage at all, and seeds stopped at 2): every protocol runs
the quick tier (20 commands, 2 seeds) on each default suite run, and
the slow tier pushes every protocol to the reference's sim_test scale
(100 commands, mod.rs:639-705) across 3 seeds.
"""

import pytest

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol

CPR = 1

# (protocol, n, f) per tier: the quick tier keeps every protocol at
# the cheap n=3/f=1 shape (caesar at n=5/f=2 alone cost ~6 min/seed);
# the slow tier runs caesar at the reference's n=5/f=2 wait-condition
# shape with everything at sim_test's 100-command scale
QUICK_SHAPES = [
    ("tempo", 3, 1),
    ("atlas", 3, 1),
    ("epaxos", 3, 1),
    ("fpaxos", 3, 1),
    ("caesar", 3, 1),
]
SLOW_SHAPES = [
    ("tempo", 3, 1),
    ("atlas", 3, 1),
    ("epaxos", 3, 1),
    ("fpaxos", 3, 1),
    ("caesar", 5, 2),
]


def run_reordered(name, n, f, conflict, seed, commands):
    planet = Planet.new()
    regions = planet.regions()[:n]
    clients = CPR * n
    dev = dev_protocol(name, clients)
    config = Config(**dev_config_kwargs(name, n, f))
    total = commands * clients
    dims = EngineDims.for_protocol(
        dev,
        n=n,
        clients=clients,
        payload=dev.payload_width(n),
        total_commands=total,
        dot_slots=total + 1,
        regions=n,
    )
    spec = make_lane(
        dev,
        planet,
        config,
        conflict_rate=conflict,
        pool_size=1,
        commands_per_client=commands,
        clients_per_region=CPR,
        process_regions=regions,
        client_regions=regions,
        dims=dims,
        # delays scale by U(0, 10): the final MCommitDot -> frontier ->
        # MGC exchange after the last completion can take several
        # seconds, so give GC the same post-completion window the
        # oracle harness uses (extra_sim_time 10 s, scaled for x10)
        extra_time_ms=30_000,
        seed=seed,
        reorder=True,
    )
    return run_lanes(dev, dims, [spec])[0], total, config


def check_invariants(name, res, total, config):
    assert res.err == 0, res.err_cause
    assert res.completed == total
    if name == "fpaxos":
        # leader-based: no fast/slow classification; GC frees a slot
        # once the f+1 write-quorum acceptors report it executed
        assert int(res.protocol_metrics["stable"].sum()) == (
            (config.f + 1) * total
        )
        return
    fast = int(res.protocol_metrics["fast_path"].sum())
    slow = int(res.protocol_metrics["slow_path"].sum())
    assert fast + slow == total
    assert int(res.protocol_metrics["stable"].sum()) == config.n * total


@pytest.mark.parametrize("name,n,f", QUICK_SHAPES)
@pytest.mark.parametrize("seed", [0, 1])
def test_reorder_invariants(name, n, f, seed):
    res, total, config = run_reordered(
        name, n, f, conflict=100, seed=seed, commands=20
    )
    check_invariants(name, res, total, config)


@pytest.mark.slow
@pytest.mark.parametrize("name,n,f", SLOW_SHAPES)
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_reorder_invariants_reference_scale(name, n, f, seed):
    """The reference's sim_test scale: 100 commands per client under
    reordering for EVERY protocol (mod.rs:639-705)."""
    res, total, config = run_reordered(
        name, n, f, conflict=100, seed=seed, commands=100
    )
    check_invariants(name, res, total, config)

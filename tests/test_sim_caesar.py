"""Caesar whole-protocol simulation tests.

Mirrors fantoch_ps/src/protocol/mod.rs sim_caesar_* tests: the reference
asserts no particular fast/slow-path split (the wait condition makes it
timing-dependent) — the value is in the harness invariants: identical
per-key execution order across processes and complete GC.
"""

import pytest

from fantoch_tpu.core import Config
from fantoch_tpu.protocol import Caesar

from harness import sim_test


def caesar_config(n, f, wait_condition):
    return Config(n=n, f=f, caesar_wait_condition=wait_condition)


def test_sim_caesar_wait_3_1():
    sim_test(Caesar, caesar_config(3, 1, True))


def test_sim_caesar_no_wait_3_1():
    sim_test(Caesar, caesar_config(3, 1, False))


def test_sim_caesar_wait_5_2():
    sim_test(Caesar, caesar_config(5, 2, True))


@pytest.mark.slow
def test_sim_caesar_no_wait_5_2():
    # ~1 min of host DES; the wait_5_2 variant stays in the quick tier
    sim_test(Caesar, caesar_config(5, 2, False))

"""Auxiliary-subsystem tests: tracing, execution-log replay, bote
search cache, shard-distribution tool (SURVEY.md §5 parity:
util.rs:73-116, execution_logger.rs + graph_executor_replay.rs,
search.rs:47-96, shard_distribution.rs).
"""

from __future__ import annotations

import asyncio
import os
import socket
import subprocess
import sys

from fantoch_tpu.bote.search import FTMetric, RankingParams, Search
from fantoch_tpu.client import ConflictPool, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.core.trace import init_tracing, tracer
from fantoch_tpu.protocol import Tempo
from fantoch_tpu.sim import Runner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_tracing_to_file(tmp_path):
    log_file = str(tmp_path / "trace.log")
    init_tracing("trace", log_file)
    try:
        planet = Planet.new()
        config = Config(n=3, f=1, gc_interval_ms=100,
                        tempo_detached_send_interval_ms=100)
        wl = Workload(
            shard_count=1,
            key_gen=ConflictPool(conflict_rate=50, pool_size=1),
            keys_per_command=1, commands_per_client=3, payload_size=1,
        )
        regions = planet.regions()[:3]
        Runner(Tempo, planet, config, wl, 1, regions, regions).run(500)
    finally:
        init_tracing("off")
    with open(log_file) as fh:
        lines = fh.readlines()
    assert any("sim.runner" in line and "<- p" in line for line in lines), (
        lines[:3]
    )


def test_execution_log_replay(tmp_path):
    """Capture an execution log from a real run-layer replica, then
    replay it through a fresh executor offline."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_run import _bind

    from fantoch_tpu.core.ids import process_ids
    from fantoch_tpu.run import client as run_client
    from fantoch_tpu.run import process as run_process

    log_path = str(tmp_path / "execution.log")

    async def main():
        config = Config(
            n=3, f=1, gc_interval_ms=25,
            tempo_detached_send_interval_ms=25,
            executor_monitor_execution_order=True,
        )
        ids = [(pid, 0) for pid in process_ids(0, 3)]
        ps = {pid: _bind() for pid, _ in ids}
        cs = {pid: _bind() for pid, _ in ids}
        paddr = {p: ("127.0.0.1", s.getsockname()[1]) for p, s in ps.items()}
        caddr = {p: ("127.0.0.1", s.getsockname()[1]) for p, s in cs.items()}
        handles = []
        for pid, shard in ids:
            handles.append(await run_process(
                Tempo, pid, shard, config,
                peer_addresses={q: paddr[q] for q, _ in ids if q != pid},
                peer_shards={q: s for q, s in ids if q != pid},
                peer_sock=ps[pid], client_sock=cs[pid],
                sorted_processes=[(pid, shard)]
                + [(q, s) for q, s in ids if q != pid],
                execution_log=log_path if pid == 1 else None,
            ))
        for h in handles:
            await h.started.wait()
        wl = Workload(
            shard_count=1,
            key_gen=ConflictPool(conflict_rate=100, pool_size=1),
            keys_per_command=1, commands_per_client=5, payload_size=1,
        )
        res = await run_client([1], {0: caddr[1]}, {0: 1}, wl)
        assert len(res.latencies_us()) == 5
        await asyncio.sleep(0.1)
        for h in handles:
            await h.stop()

    asyncio.run(main())
    assert os.path.getsize(log_path) > 0

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "executor_replay.py"),
         log_path, "--protocol", "tempo", "--n", "3", "--f", "1"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "replayed" in out.stdout
    assert "5 executions" in out.stdout, out.stdout


def test_bote_search_cache(tmp_path):
    planet = Planet.new()
    servers = planet.regions()[:7]
    search = Search(planet, servers, servers)
    params = RankingParams(
        min_mean_fpaxos_improv=float("-inf"),
        min_fairness_fpaxos_improv=float("-inf"),
        min_n=3, max_n=3, ft_metric=FTMetric.F1,
    )
    first = search.rank(params, cache_path=str(tmp_path))
    files = os.listdir(tmp_path)
    assert len(files) == 1 and files[0].startswith("search_")
    again = search.rank(params, cache_path=str(tmp_path))
    assert {n: [(c.score, c.config) for c in v] for n, v in first.items()} \
        == {n: [(c.score, c.config) for c in v] for n, v in again.items()}


def test_shard_distribution_tool():
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "shard_distribution.py"),
         "--keys", "1000", "--shards", "2", "--samples", "2000"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "shard 0" in out.stdout and "shard 1" in out.stdout

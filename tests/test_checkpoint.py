"""Durable checkpoint/restore (engine/checkpoint.py + run_sweep
wiring): artifact-level refusal gates, bit-exact interrupted-resume on
the segmented sweep path, and the tail-padding seam.

The contract under test: a sweep interrupted at a segment boundary and
resumed from its checkpoint yields byte-identical ``LaneResults`` to an
uninterrupted run (serialize both via ``LaneResults.to_json`` under
``sort_keys`` and compare the strings); a stale checkpoint (signature
or lane-ctx mismatch) or a corrupted one (truncated payload, unreadable
manifest) is *refused* with a named error, never silently misloaded.
The full-protocol × shard-path matrix rides in the slow tier; the
default tier pins the machinery on the cheap Basic/Tempo runners the
suite already compiles.
"""

import glob
import json
import os

import numpy as np
import pytest

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane
from fantoch_tpu.engine.checkpoint import (
    CheckpointCorruptError,
    CheckpointMismatchError,
    CheckpointSpec,
    SweepInterrupted,
    checkpoint_exists,
    load_artifact,
    save_artifact,
)
from fantoch_tpu.engine.protocols import (
    dev_config_kwargs,
    dev_protocol,
    partial_dev_protocol,
)
from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep
from fantoch_tpu.registry import DEV_PROTOCOLS, PARTIAL_DEV_PROTOCOLS

COMMANDS = 2
SEG = 8  # segments small enough that every lane spans several


def _blob(results) -> str:
    return json.dumps([r.to_json() for r in results], sort_keys=True)


def _specs(name: str, conflicts=(0, 100), subsets=4, shards=1):
    planet = Planet.new()
    regions = planet.regions()
    clients = 3
    pool = 1
    total = COMMANDS * clients
    if shards > 1:
        # multi-key commands need a shared pool big enough to draw
        # keys_per_cmd *unique* keys (same shape as the partial diffs)
        pool = 4
        dev = partial_dev_protocol(name, clients, shards, pool_size=pool)
        dims = EngineDims.for_partial(dev, 3, clients, total, regions=3)
        base = Config(
            **dev_config_kwargs(name, 3, 1),
            shard_count=shards,
            executor_executed_notification_interval_ms=100,
            executor_cleanup_interval_ms=100,
        )
    else:
        dev = dev_protocol(name, clients)
        dims = EngineDims.for_protocol(
            dev, n=3, clients=clients, payload=dev.payload_width(3),
            total_commands=total, dot_slots=total + 1, regions=3,
        )
        base = Config(**dev_config_kwargs(name, 3, 1))
    specs = make_sweep_specs(
        dev,
        planet,
        region_sets=[regions[i : i + 3] for i in range(subsets)],
        fs=[1],
        conflicts=list(conflicts),
        commands_per_client=COMMANDS,
        clients_per_region=1,
        dims=dims,
        config_base=base,
        pool_size=pool,
    )
    return dev, dims, specs


def _interrupt_resume(dev, dims, specs, path, **kw):
    """Stop after the first segment, then resume to completion.
    ``scan_window=1`` pins the serial segment ladder this file's
    segment-granular contracts are written against (the default window
    would cover the whole tiny batch before the first boundary);
    window-granular checkpointing rides in tests/test_scan_window.py,
    including cross-window-size resume of these very artifacts."""
    with pytest.raises(SweepInterrupted) as e:
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1,
            checkpoint=CheckpointSpec(path=path, stop_after_segments=1),
            **kw,
        )
    assert e.value.reason == "segment-limit"
    assert checkpoint_exists(path)
    resumed = run_sweep(
        dev, dims, specs, segment_steps=SEG,
        checkpoint=CheckpointSpec(path=path), **kw,
    )
    assert not checkpoint_exists(path), (
        "checkpoint must be discarded once results exist"
    )
    return resumed


# ----------------------------------------------------------------------
# artifact-level refusal gates (host only, no engine)
# ----------------------------------------------------------------------


def test_artifact_roundtrip_and_refusals(tmp_path):
    path = str(tmp_path / "ck")
    arrays = {
        "state/x": np.arange(5, dtype=np.int32),
        "ctx/y": np.ones((2, 2), np.float32),
    }
    sig = {"kind": "fantoch-tpu-checkpoint", "protocol": "p", "jax": "x"}
    save_artifact(path, arrays, sig, {"until": 3})
    loaded, manifest = load_artifact(path, sig)
    assert manifest["meta"]["until"] == 3
    np.testing.assert_array_equal(loaded["state/x"], arrays["state/x"])
    assert loaded["state/x"].dtype == np.int32
    assert loaded["ctx/y"].dtype == np.float32

    # a re-save replaces the payload atomically and GCs the old one
    save_artifact(path, arrays, sig, {"until": 4})
    assert len(glob.glob(os.path.join(path, "payload-*.npz"))) == 1

    # stale: a tampered signature component is refused BY NAME
    with pytest.raises(CheckpointMismatchError, match="protocol"):
        load_artifact(path, dict(sig, protocol="other"))

    # corrupt: a truncated payload fails its recorded sha256
    payload = glob.glob(os.path.join(path, "payload-*.npz"))[0]
    blob = open(payload, "rb").read()
    with open(payload, "wb") as fh:
        fh.write(blob[: len(blob) // 2])
    with pytest.raises(CheckpointCorruptError, match="truncated"):
        load_artifact(path, sig)

    # corrupt: an unreadable manifest
    with open(os.path.join(path, "manifest.json"), "w") as fh:
        fh.write("{not json")
    with pytest.raises(CheckpointCorruptError, match="manifest"):
        load_artifact(path, sig)


# ----------------------------------------------------------------------
# bit-exact interrupted-resume (default tier: the runners the suite
# already compiles; full matrix below in the slow tier)
# ----------------------------------------------------------------------


def test_resume_bit_exact_basic(tmp_path):
    dev, dims, specs = _specs("basic")
    control = run_sweep(dev, dims, specs, segment_steps=SEG)
    resumed = _interrupt_resume(dev, dims, specs, str(tmp_path / "ck"))
    assert _blob(resumed) == _blob(control)


def test_resume_bit_exact_both_shard_paths(tmp_path):
    dev, dims, specs = _specs("basic", subsets=4)
    for shard in (False, True):
        control = run_sweep(
            dev, dims, specs, segment_steps=SEG, shard_lanes=shard
        )
        resumed = _interrupt_resume(
            dev, dims, specs, str(tmp_path / f"ck{shard}"),
            shard_lanes=shard,
        )
        assert _blob(resumed) == _blob(control), f"shard_lanes={shard}"


def test_stale_and_wrong_spec_checkpoints_refused(tmp_path):
    dev, dims, specs = _specs("basic")
    ck = str(tmp_path / "ck")
    with pytest.raises(SweepInterrupted):
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1,
            checkpoint=CheckpointSpec(path=ck, stop_after_segments=1),
        )

    # resuming with DIFFERENT specs (conflict grid changed) must refuse
    # on the lane-ctx comparison, not silently misload
    _dev, _dims, other = _specs("basic", conflicts=(0, 50))
    with pytest.raises(CheckpointMismatchError, match="ctx"):
        run_sweep(
            dev, dims, other, segment_steps=SEG,
            checkpoint=CheckpointSpec(path=ck),
        )

    # a tampered signature (stale code/jax) is refused by name
    mpath = os.path.join(ck, "manifest.json")
    manifest = json.load(open(mpath))
    manifest["signature"]["step_jaxpr_sha256"] = "0" * 64
    with open(mpath, "w") as fh:
        json.dump(manifest, fh)
    with pytest.raises(CheckpointMismatchError, match="step_jaxpr"):
        run_sweep(
            dev, dims, specs, segment_steps=SEG,
            checkpoint=CheckpointSpec(path=ck),
        )


# ----------------------------------------------------------------------
# the tail-padding seam
# ----------------------------------------------------------------------


def test_padding_never_leaks_into_results_or_manifest(tmp_path):
    import jax

    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    # 5 specs on the 8-device mesh: 3 padded duplicates are computed
    dev, dims, specs = _specs("basic", conflicts=(100,), subsets=5)
    assert len(specs) == 5
    control = run_sweep(dev, dims, specs, segment_steps=SEG)
    assert len(control) == 5
    for lane_spec, res in zip(specs, control):
        assert res.region_rows == lane_spec.region_rows
        assert res.completed == COMMANDS * 3
        assert not res.err

    ck = str(tmp_path / "ck")
    with pytest.raises(SweepInterrupted):
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1,
            checkpoint=CheckpointSpec(path=ck, stop_after_segments=1),
        )
    manifest = json.load(open(os.path.join(ck, "manifest.json")))
    # the artifact accounts for — and CARRIES — exactly the caller's
    # lanes: padding is a property of the executing mesh (re-grown at
    # load from the bit-identical last real lane), never of the work,
    # so checkpoints interchange across device counts and layouts
    assert manifest["meta"]["lanes"] == 5
    assert "padded" not in manifest["meta"]
    assert len(manifest["meta"]["specs"]) == 5
    from fantoch_tpu.engine.checkpoint import load_artifact

    arrays, _ = load_artifact(os.path.join(ck))
    state_lanes = {
        a.shape[0] for k, a in arrays.items() if k.startswith("state/")
    }
    assert state_lanes == {5}, state_lanes
    resumed = run_sweep(
        dev, dims, specs, segment_steps=SEG,
        checkpoint=CheckpointSpec(path=ck),
    )
    assert len(resumed) == 5
    assert _blob(resumed) == _blob(control)


# ----------------------------------------------------------------------
# the full matrix: every full protocol + both partial twins, on both
# the single-device and shard_lanes=True paths (slow tier: compiles)
# ----------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("shard", [False, True])
@pytest.mark.parametrize("name", DEV_PROTOCOLS)
def test_resume_bit_exact_full_protocols(tmp_path, name, shard):
    dev, dims, specs = _specs(name, subsets=2)
    control = run_sweep(
        dev, dims, specs, segment_steps=SEG, shard_lanes=shard
    )
    resumed = _interrupt_resume(
        dev, dims, specs, str(tmp_path / "ck"), shard_lanes=shard
    )
    assert _blob(resumed) == _blob(control)


@pytest.mark.slow
@pytest.mark.parametrize("shard", [False, True])
@pytest.mark.parametrize("name", PARTIAL_DEV_PROTOCOLS)
def test_resume_bit_exact_partial_twins(tmp_path, name, shard):
    dev, dims, specs = _specs(name, conflicts=(50, 100), subsets=2,
                              shards=2)
    control = run_sweep(
        dev, dims, specs, segment_steps=SEG, shard_lanes=shard
    )
    resumed = _interrupt_resume(
        dev, dims, specs, str(tmp_path / "ck"), shard_lanes=shard
    )
    assert _blob(resumed) == _blob(control)

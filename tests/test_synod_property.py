"""Property-based Synod safety — the analog of the reference's
quickcheck suite (fantoch_ps/src/protocol/common/synod/single.rs:740+,
``a_single_value_is_chosen``, run with QUICKCHECK_TESTS=10000 in its CI).

The model mirrors the reference's: 5 processes (f=2, so phase-1 waits 3
promises and phase-2 waits 3 accepts), two competing proposers (ids 1
and 2), and hypothesis-generated action sequences where each action is
one full proposal attempt through two arbitrary quorums whose messages
and replies may independently be lost. Whatever the interleaving,
ballot races, and message loss, at most ONE distinct value may ever be
chosen — Paxos safety.

Initial acceptor values are distinct primes and the proposal function
multiplies the phase-1 reported values, so every distinct proposal path
yields a distinct value and any safety violation is observable.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

import pytest

pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from fantoch_tpu.protocol.synod import S_CHOSEN, Synod

N = 5
F = 2
PRIMES = {1: 2, 2: 3, 3: 5, 4: 7, 5: 11}

# a quorum entry: (destination process, msg lost?, reply lost?)
QuorumEntry = Tuple[int, bool, bool]
Action = Tuple[int, List[QuorumEntry], List[QuorumEntry]]


def _proposal_gen(values: Dict[int, int]) -> int:
    out = 1
    for v in values.values():
        out *= v
    return out


def _quorum(source: int):
    """Q-1 = 2 distinct non-source destinations with independent
    msg/reply loss flags (the source is always part of its quorum)."""
    others = [p for p in range(1, N + 1) if p != source]
    return st.lists(
        st.tuples(
            st.sampled_from(others), st.booleans(), st.booleans()
        ),
        min_size=2,
        max_size=2,
        unique_by=lambda e: e[0],
    )


def _actions():
    def action(source):
        return st.tuples(
            st.just(source), _quorum(source), _quorum(source)
        )

    return st.lists(
        st.sampled_from([1, 2]).flatmap(action), max_size=12
    )


def _handle_in_quorum(source_synod, synods, source, msg, quorum):
    """Deliver ``msg`` to each quorum member (unless lost) and feed
    surviving replies back to the proposer; returns the proposer's
    non-None outputs (one accept / one chosen when a quorum is hit)."""
    out = []
    for dest, msg_lost, reply_lost in quorum:
        if msg_lost:
            continue
        reply = synods[dest].handle(source, msg)
        if reply is None or reply_lost:
            continue
        result = source_synod.handle(dest, reply)
        if result is not None:
            out.append(result)
    return out


def _run(actions: List[Action]) -> Set[int]:
    synods = {
        pid: Synod(pid, N, F, _proposal_gen, PRIMES[pid])
        for pid in range(1, N + 1)
    }
    chosen: Set[int] = set()
    for source, q1, q2 in actions:
        synod = synods[source]
        prepare = synod.new_prepare()
        # the proposer is part of both its quorums: handle locally first
        local_promise = synod.handle(source, prepare)
        assert local_promise is not None
        synod.handle(source, local_promise)
        outcome = _handle_in_quorum(synod, synods, source, prepare, q1)
        if len(outcome) != 1:
            continue  # phase-1 quorum not reached (losses)
        accept = outcome[0]
        local_accepted = synod.handle(source, accept)
        if local_accepted is not None:
            synod.handle(source, local_accepted)
        outcome = _handle_in_quorum(synod, synods, source, accept, q2)
        if len(outcome) == 1 and outcome[0][0] == S_CHOSEN:
            chosen.add(outcome[0][1])
    return chosen


@settings(max_examples=500, deadline=None)
@given(_actions())
def test_a_single_value_is_chosen(actions):
    chosen = _run(actions)
    assert len(chosen) <= 1, (
        f"safety violation: two values chosen {chosen}"
    )


@pytest.mark.slow
@settings(max_examples=5000, deadline=None)
@given(_actions())
def test_a_single_value_is_chosen_deep(actions):
    """The reference CI's depth (QUICKCHECK_TESTS=10000; half here,
    with hypothesis shrinking doing more work per failure)."""
    chosen = _run(actions)
    assert len(chosen) <= 1


def test_two_proposers_interleaved_deterministic():
    """A fixed adversarial interleaving as a readable anchor: proposer
    2 overtakes proposer 1 between its phases — proposer 1's stale
    accept must be rejected and only one value survives."""
    chosen = _run(
        [
            # p1 completes phase-1 at {3, 4}, then loses its accepts
            (1, [(3, False, False), (4, False, False)],
                [(3, True, True), (4, True, True)]),
            # p2 runs both phases cleanly at {3, 5}
            (2, [(3, False, False), (5, False, False)],
                [(3, False, False), (5, False, False)]),
            # p1 retries end-to-end at {4, 5}
            (1, [(4, False, False), (5, False, False)],
                [(4, False, False), (5, False, False)]),
        ]
    )
    assert len(chosen) == 1

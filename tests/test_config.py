"""Quorum-size formula tests (mirrors fantoch/src/config.rs:461-549)."""

from fantoch_tpu.core import Config


def test_basic_parameters():
    assert Config(7, 1).basic_quorum_size() == 2
    assert Config(7, 2).basic_quorum_size() == 3
    assert Config(7, 3).basic_quorum_size() == 4


def test_atlas_parameters():
    assert Config(7, 1).atlas_quorum_sizes() == (4, 2)
    assert Config(7, 2).atlas_quorum_sizes() == (5, 3)
    assert Config(7, 3).atlas_quorum_sizes() == (6, 4)


def test_epaxos_parameters():
    ns = [3, 5, 7, 9, 11, 13, 15, 17]
    expected = [(2, 2), (3, 3), (5, 4), (6, 5), (8, 6), (9, 7), (11, 8), (12, 9)]
    assert [Config(n, 0).epaxos_quorum_sizes() for n in ns] == expected


def test_caesar_parameters():
    ns = [3, 5, 7, 9, 11]
    expected = [(3, 2), (4, 3), (6, 4), (7, 5), (9, 6)]
    assert [Config(n, 0).caesar_quorum_sizes() for n in ns] == expected


def test_tempo_parameters():
    assert Config(7, 1).tempo_quorum_sizes() == (4, 2, 4)
    assert Config(7, 2).tempo_quorum_sizes() == (5, 3, 4)
    assert Config(7, 1, tempo_tiny_quorums=True).tempo_quorum_sizes() == (2, 2, 6)
    assert Config(7, 2, tempo_tiny_quorums=True).tempo_quorum_sizes() == (4, 3, 5)

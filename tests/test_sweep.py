"""Mesh-sharded sweep driver test: a Tempo sweep over the virtual
8-device CPU mesh must produce err-free, complete lanes with the
reference's f=1 fast-path guarantee, independent of mesh sharding."""

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.protocols import TempoDev
from fantoch_tpu.parallel import make_sweep_specs, run_sweep

COMMANDS = 10


def test_tempo_sweep_on_mesh():
    import jax

    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    planet = Planet.new()
    regions = planet.regions()
    region_sets = [regions[i : i + 3] for i in range(3)]

    clients = 3
    tempo = TempoDev(keys=1 + clients)
    dims = EngineDims.for_protocol(
        tempo,
        n=3,
        clients=clients,
        payload=tempo.payload_width(3),
        total_commands=COMMANDS * clients,
        dot_slots=COMMANDS * clients + 1,
        regions=3,
    )
    specs = make_sweep_specs(
        tempo,
        planet,
        region_sets=region_sets,
        fs=[1],
        conflicts=[0, 100],
        commands_per_client=COMMANDS,
        clients_per_region=1,
        dims=dims,
        config_base=Config(
            n=3, f=1, gc_interval_ms=100,
            tempo_detached_send_interval_ms=100,
        ),
    )
    assert len(specs) == 6  # 3 region sets × 1 f × 2 conflicts
    results = run_sweep(tempo, dims, specs)
    assert len(results) == 6
    for spec, res in zip(specs, results):
        assert not res.err
        total = COMMANDS * 3
        assert res.completed == total
        fast = int(res.protocol_metrics["fast_path"].sum())
        slow = int(res.protocol_metrics["slow_path"].sum())
        assert fast + slow == total
        assert slow == 0  # f=1 ⇒ 100% fast path
        assert int(res.protocol_metrics["stable"].sum()) == 3 * total

"""Fleet campaigns (fantoch_tpu/fleet): lease-sharded multi-worker
execution over one shared campaign dir.

Default tier pins the three core invariants on the suite's shared
compiled Basic runner (plus a tempo merge group): lease contention
(exactly one winner, loser moves on), TTL-gated reclaim (never before
expiry, including across a real ``kill -9`` mid-unit), and the
determinism headline — an N-worker fleet's merged ``results.jsonl``
byte-identical to the 1-worker control AND to the single-process
``campaign`` manager's output. Slow tier widens the merge identity to
every full protocol and to fuzz campaigns.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from fantoch_tpu.campaign import campaign_from_json, run_campaign
from fantoch_tpu.fleet import (
    FleetError,
    claim_unit,
    lease_holder,
    merge_campaign,
    run_fleet_worker,
)
from fantoch_tpu.fleet.worker import (
    append_worker_journal,
    read_all_journals,
    sweep_done_units,
)
from fantoch_tpu.registry import check_worker_id, worker_id_ok

# mirrors tests/test_campaign.py shapes so fleet units reuse the
# suite's compiled Basic segment runner; batch_lanes=1 gives 4 units —
# enough for real interleaving between two workers. scan_window=1
# pins the per-segment ladder the stop_after_segments interruption
# tests count on (the default window would finish these tiny units
# before the first boundary); window-granular + AOT fleets are pinned
# in tests/test_scan_window.py.
SWEEP_GRID = {
    "kind": "sweep",
    "protocols": ["basic"],
    "ns": [3],
    "conflicts": [0, 100],
    "subsets": 2,
    "commands_per_client": 2,
    "batch_lanes": 1,
    "segment_steps": 8,
    "scan_window": 1,
}


def _read(path):
    with open(path, "rb") as fh:
        return fh.read()


# ----------------------------------------------------------------------
# worker ids + lease protocol (device-free)
# ----------------------------------------------------------------------


def test_worker_id_rules():
    assert worker_id_ok("w0") and worker_id_ok("tpu-pod_3")
    # non-ASCII alphanumerics are refused: ids become filenames
    for bad in ("", "a.b", "a/b", "lock", "stale", "tmp", "x" * 65,
                ".hidden", "wé", "٢", None, 7):
        assert not worker_id_ok(bad), bad
    with pytest.raises(ValueError, match="worker id"):
        check_worker_id("a.b")


def test_lease_contention_exactly_one_winner(tmp_path):
    """Two (here: eight) workers race one unit — exactly one wins,
    every loser gets None and moves on. Repeated rounds, fresh unit
    each time, all claims released afterwards."""
    d = str(tmp_path)
    for rnd in range(10):
        unit = f"proto/n3/b{rnd}"
        wins = []
        barrier = threading.Barrier(8)

        def race(i, unit=unit, wins=wins, barrier=barrier):
            barrier.wait()
            lease = claim_unit(d, unit, f"w{i}", ttl_s=30.0)
            if lease is not None:
                wins.append(lease)

        threads = [
            threading.Thread(target=race, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(wins) == 1, f"round {rnd}: {len(wins)} winners"
        holder = lease_holder(d, unit)
        assert holder is not None and holder[0] == wins[0].worker
        wins[0].release()
        assert lease_holder(d, unit) is None


def test_work_stealing_scan_order_cuts_contention(tmp_path):
    """The lease-aware work-stealing satellite: 8 workers claiming
    from 8 units through their worker-id-rotated scan orders suffer
    strictly fewer contended claims (claim_unit → None) than the
    canonical everyone-starts-at-unit-0 scan, while every unit is
    still claimed exactly once and the unit SET is unchanged —
    rotation is a throughput hint only; merge order never depends on
    it."""
    from fantoch_tpu.fleet.worker import worker_scan_order

    units = [f"p/n3/b{i}" for i in range(8)]
    workers = [f"w{i}" for i in range(8)]
    # rotation preserves the set and is a true rotation
    for w in workers:
        order = worker_scan_order(units, w)
        assert sorted(order) == sorted(units)
        off = order.index(units[0])
        assert order == units[-off:] + units[:-off] or off == 0

    def drain(subdir, orders):
        """Replay the claim scan: each worker walks its order until a
        claim succeeds; count the contended misses along the way."""
        d = str(tmp_path / subdir)
        misses, claimed = 0, []
        for w, order in zip(workers, orders):
            for u in order:
                lease = claim_unit(d, u, w, ttl_s=30.0)
                if lease is None:
                    misses += 1
                else:
                    claimed.append(u)
                    break
        assert sorted(claimed) == sorted(units)  # all drained once
        return misses

    canonical = drain("canon", [list(units)] * 8)
    rotated = drain(
        "rot", [worker_scan_order(units, w) for w in workers]
    )
    # canonical scan: worker k misses every earlier claim (28 total);
    # the rotated scan must cut that — with this worker-id spread it
    # eliminates contention outright
    assert canonical == 28
    assert rotated < canonical
    assert rotated == 0


def test_claim_backoff_deterministic_bounded_and_cuts_attempts(
    tmp_path,
):
    """The lease-claim backoff satellite: a lost claim used to retry
    the next unit immediately — a hot spin over the lease dir when
    most of the grid is held. The fix is ``claim_backoff_s`` (pure
    function of (worker_id, miss streak) — no wall clock, no
    ``random.*``, GL402-safe since it only feeds ``time.sleep``) plus
    a done-set refresh after every miss, so units whose holders finish
    during the bought time are skipped without burning a claim. The
    drain replay pins the effect: the backoff policy spends strictly
    fewer claim attempts than the immediate-retry policy on the same
    8×8 grid while journaling the SAME completions — and the merged
    bytes, which erase completion order, cannot tell them apart."""
    from fantoch_tpu.engine.checkpoint import canonical_json
    from fantoch_tpu.fleet.worker import claim_backoff_s

    # pure, bounded, worker-keyed: identical on every call, zero for a
    # zero streak, capped at the module cap, and phase-shifted between
    # workers so contenders desynchronize instead of re-colliding
    for w in ("w0", "w1", "long-worker-id_9"):
        assert claim_backoff_s(w, 0) == 0.0
        seq = [claim_backoff_s(w, m) for m in range(1, 12)]
        assert seq == [claim_backoff_s(w, m) for m in range(1, 12)]
        assert all(0.0 < s <= 0.25 for s in seq)
    assert claim_backoff_s("w0", 3) != claim_backoff_s("w1", 3)

    units = [f"p/n3/b{i}" for i in range(8)]
    workers = [f"w{i}" for i in range(8)]
    WORK_TICKS = 4  # ticks a holder runs its unit before journaling

    def drain(backoff):
        """Lockstep replay of the sweep claim loop (one scan step per
        worker per tick) against a shared lease table + journal —
        time-free, so the pinned counts are exact. ``backoff=False``
        is the old immediate-retry policy; ``backoff=True`` sleeps a
        streak-scaled number of ticks after a miss and refreshes the
        done-set on wake, exactly the shipped loop's moves."""
        held, journal = {}, []
        snapshot = {w: set() for w in workers}
        pos = {w: 0 for w in workers}
        holding = {w: None for w in workers}
        work_left = {w: 0 for w in workers}
        sleep = {w: 0 for w in workers}
        misses = {w: 0 for w in workers}
        active = {w: True for w in workers}
        pass_completed = {w: 0 for w in workers}
        attempts = 0
        ticks = 0
        while len(journal) < len(units) or any(
            holding[w] for w in workers
        ):
            ticks += 1
            assert ticks < 10_000
            for w in workers:
                if not active[w]:
                    continue
                if holding[w] is not None:
                    work_left[w] -= 1
                    if work_left[w] <= 0:
                        u = holding[w]
                        journal.append(u)
                        del held[u]
                        holding[w] = None
                        snapshot[w] = set(journal)
                        pass_completed[w] += 1
                    continue
                if sleep[w] > 0:
                    sleep[w] -= 1
                    if sleep[w] == 0:
                        # the refresh bought by the backoff
                        snapshot[w] = set(journal)
                    continue
                while (
                    pos[w] < len(units)
                    and units[pos[w]] in snapshot[w]
                ):
                    pos[w] += 1
                if pos[w] >= len(units):
                    # pass bottom: exit once a pass completes nothing
                    # (or the grid is drained), else restart the pass
                    # on a fresh journal read — the real loop's gate
                    if not pass_completed[w] or (
                        len(journal) == len(units)
                    ):
                        active[w] = False
                    else:
                        pass_completed[w] = 0
                        pos[w] = 0
                        snapshot[w] = set(journal)
                    continue
                u = units[pos[w]]
                attempts += 1
                if u in journal:
                    # completed after this worker's snapshot: the real
                    # loop's under-lease re-check discards it and
                    # refreshes (both policies)
                    snapshot[w] = set(journal)
                    continue
                if u in held:
                    misses[w] += 1
                    pos[w] += 1
                    if backoff:
                        sleep[w] = min(1 << min(misses[w], 3), 8)
                else:
                    held[u] = w
                    holding[w] = u
                    work_left[w] = WORK_TICKS
                    misses[w] = 0
        return attempts, journal

    spin_attempts, spin_done = drain(backoff=False)
    back_attempts, back_done = drain(backoff=True)
    assert sorted(spin_done) == sorted(back_done) == sorted(units)
    # the hot spin: 8 wins plus a miss for every held unit every
    # worker scans past, across every pass until its exit gate
    assert spin_attempts == 64
    # backoff + refresh-on-wake cuts the claim traffic outright
    assert back_attempts < spin_attempts
    assert back_attempts == 46
    # merge-bytes identity: journal both policies' completions and
    # check the canonical-order merged lines agree byte for byte —
    # backoff is a lease-traffic hint only, never a results change
    merged = []
    for name, order in (("spin", spin_done), ("back", back_done)):
        d = str(tmp_path / name)
        for i, u in enumerate(order):
            append_worker_journal(
                d, f"w{i % 8}",
                {"kind": "batch", "id": u, "results": [{"err": 0}]},
            )
        done = sweep_done_units(read_all_journals(d))
        merged.append(
            [
                canonical_json(
                    {"batch": u, "lane": 0, "result": done[u][0]}
                )
                for u in units
            ]
        )
    assert merged[0] == merged[1]


def test_lease_reclaim_only_after_ttl(tmp_path):
    """The reclaim gate: a live (heartbeated) lease is never stolen;
    an expired one is reclaimable by exactly one claimant."""
    d = str(tmp_path)
    a = claim_unit(d, "u/1", "a", ttl_s=0.6)
    assert a is not None
    # live lease: competitor refused outright
    assert claim_unit(d, "u/1", "b", ttl_s=0.6) is None
    # heartbeats keep it alive past the original TTL
    for _ in range(4):
        time.sleep(0.25)
        a.heartbeat()
    assert claim_unit(d, "u/1", "b", ttl_s=0.6) is None, (
        "reclaim fired on a heartbeated lease"
    )
    # dead holder: claim succeeds only once the mtime is older than TTL
    time.sleep(0.7)
    b = claim_unit(d, "u/1", "b", ttl_s=0.6)
    assert b is not None and lease_holder(d, "u/1")[0] == "b"
    b.release()


def test_lease_released_unit_immediately_reclaimable(tmp_path):
    d = str(tmp_path)
    a = claim_unit(d, "u/2", "a", ttl_s=30.0)
    a.release()
    b = claim_unit(d, "u/2", "b", ttl_s=30.0)
    assert b is not None
    b.release()


def test_conflicting_duplicate_unit_results_refused(tmp_path):
    """Two journals completing one unit with DIFFERENT rows break the
    determinism contract — the merge must refuse, never pick one."""
    d = str(tmp_path)
    append_worker_journal(
        d, "a", {"kind": "batch", "id": "x/b0", "results": [{"err": 0}]}
    )
    append_worker_journal(
        d, "b", {"kind": "batch", "id": "x/b0", "results": [{"err": 1}]}
    )
    with pytest.raises(FleetError, match="DIFFERING"):
        sweep_done_units(read_all_journals(d))


# ----------------------------------------------------------------------
# multi-worker merge determinism (the headline invariant)
# ----------------------------------------------------------------------


def test_two_worker_fleet_merge_byte_identical_to_control(tmp_path):
    """Interleaved workers (w1 two units, w2 the rest, w1 journals
    consulted by w2) merge to a results.jsonl byte-identical to BOTH
    the 1-worker fleet control and the single-process campaign
    manager's output for the same grid."""
    spec = campaign_from_json(SWEEP_GRID)

    mgr = str(tmp_path / "mgr")
    assert run_campaign(mgr, spec)["done"]

    solo = str(tmp_path / "solo")
    s = run_fleet_worker(solo, spec, worker_id="solo")
    assert s["done"] and s["units_completed_here"] == 4
    assert merge_campaign(solo)["merged"]

    fleet = str(tmp_path / "fleet")
    s1 = run_fleet_worker(fleet, spec, worker_id="w1",
                          stop_after_units=2)
    assert s1["interrupted"] == "unit-limit"
    assert s1["units_completed_here"] == 2 and not s1["done"]
    s2 = run_fleet_worker(fleet, None, worker_id="w2")
    assert s2["done"] and s2["units_completed_here"] == 2
    merged = merge_campaign(fleet)
    assert merged["merged"] and merged["errors"] == 0

    control = _read(os.path.join(mgr, "results.jsonl"))
    assert control
    assert _read(os.path.join(solo, "results.jsonl")) == control
    assert _read(os.path.join(fleet, "results.jsonl")) == control
    # worker-scoped journals, not the shared single-process file
    assert not os.path.exists(os.path.join(fleet, "journal.jsonl"))
    assert sorted(
        os.listdir(os.path.join(fleet, "journals"))
    ) == ["w1.jsonl", "w2.jsonl"]


def test_abandoned_unit_resumed_by_other_worker_bit_exact(tmp_path):
    """Worker a is interrupted mid-unit (deterministic segment-limit
    stand-in for preemption): the unit's checkpoint is durable in the
    SHARED dir and its lease released, so worker b resumes it — not
    from scratch — and the merged results stay byte-identical."""
    spec = campaign_from_json(SWEEP_GRID)
    mgr = str(tmp_path / "mgr")
    run_campaign(mgr, spec)

    fleet = str(tmp_path / "fleet")
    s1 = run_fleet_worker(fleet, spec, worker_id="a",
                          stop_after_segments=1)
    assert s1["interrupted"] == "segment-limit"
    assert s1["units_completed_here"] == 0
    # durable checkpoint under the shared dir, lease back in the pool
    assert glob.glob(os.path.join(fleet, "ckpt", "*", "manifest.json"))
    assert lease_holder(fleet, "basic/n3/b0") is None
    s2 = run_fleet_worker(fleet, None, worker_id="b")
    assert s2["done"]
    assert merge_campaign(fleet)["merged"]
    assert _read(os.path.join(fleet, "results.jsonl")) == _read(
        os.path.join(mgr, "results.jsonl")
    )


def test_merge_refuses_missing_units_and_empty_dir(tmp_path):
    from fantoch_tpu.campaign import CampaignError

    with pytest.raises(CampaignError, match="nothing to merge"):
        merge_campaign(str(tmp_path / "missing"))
    spec = campaign_from_json(SWEEP_GRID)
    fleet = str(tmp_path / "fleet")
    run_fleet_worker(fleet, spec, worker_id="w1", stop_after_units=1)
    merged = merge_campaign(fleet)
    assert not merged["merged"]
    assert merged["units_done"] == 1 and merged["missing_units"]
    assert not os.path.exists(os.path.join(fleet, "results.jsonl"))


def test_fleet_worker_killed_mid_unit_reclaimed_bit_exact(tmp_path):
    """The real preemption shape: a subprocess worker is SIGKILLed
    mid-unit; its lease expires (short TTL), a second worker reclaims
    the unit, resumes its checkpoint, and the merged results are
    byte-identical to the uninterrupted control."""
    spec = campaign_from_json(SWEEP_GRID)
    mgr = str(tmp_path / "mgr")
    run_campaign(mgr, spec)

    fleet = str(tmp_path / "fleet")
    grid = json.dumps(SWEEP_GRID)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "fantoch_tpu", "--platform", "cpu",
            "fleet", "--dir", fleet, "--grid", grid,
            "--worker-id", "doomed", "--ttl-s", "1.5",
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        # wait until the worker holds a lease and has a checkpoint in
        # flight — i.e. it is genuinely mid-unit — then kill -9
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if glob.glob(
                os.path.join(fleet, "ckpt", "*", "manifest.json")
            ) or glob.glob(os.path.join(fleet, "leases", "*.lock")):
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # the reclaimer: loops like any preempted-pool supervisor would —
    # a pass can stop while the dead worker's lease is still within
    # TTL, so retry until the grid drains
    deadline = time.monotonic() + 120
    while True:
        s = run_fleet_worker(fleet, spec, worker_id="reclaimer",
                             ttl_s=1.5)
        if s["done"]:
            break
        assert time.monotonic() < deadline, s
        time.sleep(0.5)
    assert merge_campaign(fleet)["merged"]
    assert _read(os.path.join(fleet, "results.jsonl")) == _read(
        os.path.join(mgr, "results.jsonl")
    )


# ----------------------------------------------------------------------
# fleet × mesh_shard composition
# ----------------------------------------------------------------------


def test_fleet_mesh_shard_campaign_matches_reference(tmp_path):
    """A fleet whose units run mesh-partitioned (campaign-grid
    mesh_shard) merges byte-identically to the plain single-device
    campaign — the layout must be result-invisible end to end."""
    spec = campaign_from_json(SWEEP_GRID)
    ref = str(tmp_path / "ref")
    run_campaign(ref, spec)

    mspec = campaign_from_json(dict(SWEEP_GRID, mesh_shard=True))
    fleet = str(tmp_path / "fleet")
    s = run_fleet_worker(fleet, mspec, worker_id="w1")
    assert s["done"]
    assert merge_campaign(fleet)["merged"]
    a = _read(os.path.join(fleet, "results.jsonl"))
    b = _read(os.path.join(ref, "results.jsonl"))
    # the results lines differ only in nothing: same batches, same
    # lanes, same bytes — mesh_shard is not part of the batch ids
    assert a == b


# ----------------------------------------------------------------------
# slow tier: all protocols + fuzz fleet
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_fleet_merge_identity_all_protocols(tmp_path):
    grid = {
        "kind": "sweep",
        "protocols": [
            "basic", "fpaxos", "tempo", "atlas", "epaxos", "caesar"
        ],
        "ns": [3],
        "conflicts": [100],
        "subsets": 1,
        "commands_per_client": 2,
        "batch_lanes": 1,
        "segment_steps": 64,
    }
    spec = campaign_from_json(grid)
    mgr = str(tmp_path / "mgr")
    assert run_campaign(mgr, spec)["done"]
    fleet = str(tmp_path / "fleet")
    run_fleet_worker(fleet, spec, worker_id="w1", stop_after_units=3)
    s = run_fleet_worker(fleet, None, worker_id="w2")
    assert s["done"]
    assert merge_campaign(fleet)["merged"]
    assert _read(os.path.join(fleet, "results.jsonl")) == _read(
        os.path.join(mgr, "results.jsonl")
    )


@pytest.mark.slow
def test_fuzz_fleet_two_workers_summary_identical(tmp_path):
    """A fuzz campaign's points are fleet units: two workers handing a
    point's chunks across the journaled generator position must merge
    to a summary.json byte-identical to the 1-worker control."""
    grid = {
        "kind": "fuzz",
        "protocols": ["tempo"],
        "ns": [3],
        "schedules": 8,
        "chunk": 4,
        "commands_per_client": 5,
        "seed": 7,
        "confirm": False,
    }
    spec = campaign_from_json(grid)

    solo = str(tmp_path / "solo")
    s = run_fleet_worker(solo, spec, worker_id="solo")
    assert s["done"]
    assert merge_campaign(solo)["merged"]

    fleet = str(tmp_path / "fleet")
    # budget 0: at least one chunk of progress, then stop — the point
    # lease is released with the generator position journaled
    s1 = run_fleet_worker(fleet, spec, worker_id="w1", budget_s=0.0)
    assert not s1["done"] and s1["interrupted"] == "budget exhausted"
    s2 = run_fleet_worker(fleet, None, worker_id="w2")
    assert s2["done"]
    assert merge_campaign(fleet)["merged"]
    assert _read(os.path.join(fleet, "summary.json")) == _read(
        os.path.join(solo, "summary.json")
    )

"""lint/report.py baseline edge cases: duplicate stable IDs within one
run (the count-aware allowance), per-ID counts *shrinking* (the
stale-baseline advisory path), and `load_baseline` round-tripping both
the checked-in layout and a plain id→count map through
`write_baseline`."""

import json

from fantoch_tpu.lint.report import (
    Finding,
    LintReport,
    load_baseline,
    write_baseline,
)


def _f(rule="GL001", audit="syn", anchor="a.py:f:mul"):
    return Finding(rule, audit, anchor, "msg")


def test_duplicate_ids_consume_allowance_per_occurrence():
    """Two findings with one stable ID are two occurrences: a baseline
    allowing one suppresses exactly one — the second is a regression
    (a new unclamped multiply in an already-baselined function must
    not hide behind the existing entry)."""
    report = LintReport(findings=[_f(), _f()])
    fid = _f().id
    assert report.counts() == {fid: 2}
    assert len(report.regressions({fid: 1})) == 1
    assert report.regressions({fid: 2}) == []
    # with no baseline at all, both are regressions
    assert len(report.regressions(None)) == 2


def test_shrinking_count_is_stale_not_regression():
    """A fixed finding leaves its baseline allowance over-provisioned:
    that's advisory (stale), never a failure — narrowed runs
    (--protocols) legitimately observe fewer findings."""
    fid = _f().id
    report = LintReport(findings=[_f()])
    baseline = {fid: 3, "GL999:gone:b.py:g:add": 1}
    assert report.regressions(baseline) == []
    stale = report.stale_baseline_ids(baseline)
    assert fid in stale  # 1 observed < 3 allowed
    assert "GL999:gone:b.py:g:add" in stale  # 0 observed < 1 allowed
    # an exactly-consumed allowance is not stale
    assert report.stale_baseline_ids({fid: 1}) == []


def test_write_baseline_round_trips_through_load(tmp_path):
    path = str(tmp_path / "baseline.json")
    report = LintReport(findings=[_f(), _f(), _f(anchor="a.py:g:add")])
    write_baseline(path, report)
    loaded = load_baseline(path)
    assert loaded == report.counts()
    # the written file carries the checked-in layout (a findings map
    # under a comment), which load_baseline unwraps
    raw = json.load(open(path))
    assert set(raw) == {"_comment", "findings"}


def test_write_baseline_never_bakes_in_cost_findings(tmp_path):
    """Cost-family findings (GL2xx) gate against cost_baseline.json and
    exist only when something is already wrong — writing one into the
    suppression baseline would permanently hide a live kernel/VMEM/lane
    regression from CI, so `--cost --write-baseline` must drop them."""
    path = str(tmp_path / "baseline.json")
    report = LintReport(
        findings=[
            _f(),
            Finding("GL201", "tempo", "core.py:_lane_step:kernels", "m"),
            Finding("GL203", "tempo", "core.py:step:reduce_sum", "m"),
        ]
    )
    write_baseline(path, report)
    loaded = load_baseline(path)
    assert loaded == {_f().id: 1}
    assert not any(k.startswith("GL2") for k in loaded)


def test_load_baseline_plain_map_with_comments(tmp_path):
    """A hand-written plain {id: count} map (no findings wrapper) loads
    identically, with _-prefixed comment keys dropped."""
    path = tmp_path / "plain.json"
    plain = {"_why": "hand-written", "GL001:syn:a.py:f:mul": 2}
    path.write_text(json.dumps(plain))
    assert load_baseline(str(path)) == {"GL001:syn:a.py:f:mul": 2}
    # and a plain map round-trips through write_baseline: rebuild a
    # report with matching counts, write, re-load
    report = LintReport(findings=[_f(), _f()])
    out = tmp_path / "rewritten.json"
    write_baseline(str(out), report)
    assert load_baseline(str(out)) == {"GL001:syn:a.py:f:mul": 2}

"""Schedule-fuzzing subsystem tests (mc/fuzz.py, mc/shrink.py,
engine/monitor.py).

Fast tier: monitor trace-gating (a fuzz-disabled engine compiles zero
monitor ops and carries zero monitor state), jitter plan serialization
and the device/host draw agreement, perturbation drawing invariants,
and ddmin/artifact unit behavior — no compiled engine runs.

Slow tier (one compiled fuzz runner per protocol variant): the
injected-bug regression — a deliberately broken Tempo (stability
threshold off by one) must be caught by the fuzzer within a bounded
schedule budget, host-confirm, and shrink to a replayable artifact of
<= 10 perturbations — plus the zero-violation check on correct Tempo
and bit-exact host replay of jittered device schedules.
"""

import json

import numpy as np
import pytest

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, FaultPlan, make_lane
from fantoch_tpu.engine.core import _lane_step, init_lane_state
from fantoch_tpu.engine.faults import (
    NO_FAULTS,
    FaultFlags,
    fault_ctx,
    jitter_draw,
)
from fantoch_tpu.engine.monitor import (
    VIOL_ORDER,
    mon_exec,
    viol_names,
)
from fantoch_tpu.engine.protocols import TempoDev, dev_config_kwargs
from fantoch_tpu.lint.gating import alpha_equivalent, check_gating
from fantoch_tpu.lint.jaxpr import trace_step
from fantoch_tpu.mc.fuzz import (
    FuzzSpec,
    draw_plans,
    host_check,
    replay_artifact,
    run_fuzz_point,
)
from fantoch_tpu.mc.shrink import (
    RecordingPlan,
    components_plan,
    ddmin,
    plan_components,
)

import jax


def _tempo_lane(monitor_keys=0, faults_plan=None):
    n, clients, commands = 3, 3, 5
    config = Config(**dev_config_kwargs("tempo", n, 1))
    planet = Planet.new()
    regions = planet.regions()[:n]
    dev = TempoDev.for_load(keys=1 + clients, clients=clients)
    total = commands * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    spec = make_lane(
        dev, planet, config, conflict_rate=100, pool_size=1,
        commands_per_client=commands, clients_per_region=1,
        process_regions=regions, client_regions=regions, dims=dims,
        faults=faults_plan,
    )
    st = init_lane_state(dev, dims, spec.ctx, monitor_keys=monitor_keys)
    return dev, dims, spec, st


# ----------------------------------------------------------------------
# trace gating: a fuzz-disabled engine pays nothing
# ----------------------------------------------------------------------


def test_monitors_trace_gated_out():
    """monitor_keys=0 must (a) add no monitor state, (b) trace a step
    that is *structurally identical* — alpha-equivalent, not just
    equation-count-equal — to a feature-stripped step in which every
    monitor entry point and fault draw is stubbed out. The structural
    differ (fantoch_tpu/lint/gating.py) replaces the brittle raw
    eqn-count pin this test used to carry (5355 == 5355)."""
    dev, dims, spec, st0 = _tempo_lane(monitor_keys=0)
    assert "mon_hash" not in st0 and "viol" not in st0
    _, _, _, st1 = _tempo_lane(monitor_keys=4)
    assert st1["mon_hash"].shape == (dims.N, 4)

    trace0 = trace_step(dev, dims, st0, spec.ctx, name="tempo-gated")
    assert check_gating(trace0) == [], check_gating(trace0)

    # the monitored step must NOT be equivalent (monitors trace real
    # work when enabled — otherwise the differ proves nothing)
    trace1 = trace_step(
        dev, dims, st1, spec.ctx, monitor_keys=4, name="tempo-mon"
    )
    ok, _why = alpha_equivalent(trace0.closed, trace1.closed)
    assert not ok, "monitored step traced no extra monitor ops"

    def step(mk):
        def f(s, c):
            return _lane_step(dev, dims, s, c, False, NO_FAULTS, mk)
        return f

    # the disabled step's output state mirrors its input structure —
    # no monitor leaves appear anywhere in the traced pytree
    out_tree = jax.eval_shape(step(0), st0, spec.ctx)
    assert sorted(out_tree.keys()) == sorted(st0.keys())


def test_mon_exec_noop_without_monitor_state():
    """The protocol hooks are free when fuzzing is off: without the
    merged monitor keys, mon_exec returns its input dict unchanged (the
    very same object — nothing traced)."""
    ps = {"clocks": np.zeros((4,), np.int32)}
    assert mon_exec(ps, 1, 0, 1, True) is ps


# ----------------------------------------------------------------------
# jitter plans: serialization, flags, device/host draw agreement
# ----------------------------------------------------------------------


def test_jitter_plan_flags_and_roundtrip():
    plan = FaultPlan(jitter_max=8, jitter_seed=5)
    assert plan.flags == FaultFlags(jitter=True)
    assert not plan.is_noop() and not plan.host_only()
    again = FaultPlan.from_json(plan.meta())
    assert again == plan

    explicit = FaultPlan(
        jitter_overrides={(0, 1, 7): 5},
        drop_list=((1, 2, 3),),
        horizon_ms=1000,
        crashes={2: 400},
    )
    assert explicit.host_only()
    again = FaultPlan.from_json(explicit.meta())
    assert again.jitter_overrides == {(0, 1, 7): 5}
    assert again.drop_list == ((1, 2, 3),)
    assert again.crashes == {2: 400}


def test_host_only_plans_rejected_by_device():
    explicit = FaultPlan(jitter_overrides={(0, 1, 7): 5})

    class _Dims:
        N = 3

    with pytest.raises(AssertionError):
        fault_ctx(explicit, _Dims())


def test_explicit_lossy_plan_requires_horizon():
    with pytest.raises(AssertionError):
        FaultPlan(drop_list=((0, 1, 2),))  # lossy, no horizon


def test_jitter_table_matches_device_draw():
    """The host oracle's precomputed table and the device's in-loop
    threefry draw must agree on every (src, dst, channel index)."""
    plan = FaultPlan(jitter_max=6, jitter_seed=11)
    table = plan.jitter_table(n=3, kmax=32)
    assert table.min() >= 1 and table.max() <= 6
    assert len(np.unique(table)) > 1, "degenerate jitter draws"
    key = plan.jitter_key()
    for s, d, k in [(0, 1, 0), (2, 0, 31), (1, 2, 17)]:
        got = int(jitter_draw(key, s, d, k, 6))
        assert got == int(table[s, d, k]), (s, d, k)


def test_jitter_plan_wire_applies_override_and_droplist():
    plan = FaultPlan(
        jitter_overrides={(0, 1, 3): 4},
        drop_list=((0, 2, 1),),
        horizon_ms=1000,
    )
    delay, lost = plan.wire(0, 1, 10, 50, 3)
    assert (delay, lost) == (200, False)
    delay, lost = plan.wire(0, 1, 10, 50, 4)  # un-overridden message
    assert (delay, lost) == (50, False)
    _, lost = plan.wire(0, 2, 10, 50, 1)
    assert lost


# ----------------------------------------------------------------------
# perturbation drawing
# ----------------------------------------------------------------------


def test_draw_plans_deterministic_and_bounded():
    spec = FuzzSpec(
        protocol="fpaxos", n=3, f=1, schedules=64, seed=9,
        crash_share=0.4, drop_share=0.3,
    )
    config = Config(**dev_config_kwargs("fpaxos", 3, 1))
    from fantoch_tpu.engine.protocols import FPaxosDev

    a = draw_plans(spec, config, FPaxosDev)
    b = draw_plans(spec, config, FPaxosDev)
    assert a == b, "plans must be a pure function of the root seed"
    crash = [p for p in a if p.crashes]
    drops = [p for p in a if p.drop_bp]
    assert crash and drops, "the mix must include both fault kinds"
    leader_row = config.leader - 1
    for p in crash:
        assert len(p.crashes) <= config.f
        assert leader_row not in p.crashes, (
            "crashing the leader halts every client - nothing to check"
        )
    for p in drops:
        assert p.horizon_ms is not None, "lossy plans need a horizon"
    assert all(p.jitter_max == spec.jitter_max for p in a)


# ----------------------------------------------------------------------
# shrinker units
# ----------------------------------------------------------------------


def test_ddmin_reduces_to_culprit():
    comps = [("jit", (0, 1, k), 2) for k in range(40)]
    culprit = ("jit", (2, 0, 99), 7)
    comps.insert(17, culprit)

    calls = []

    def test_fn(cand):
        calls.append(len(cand))
        return "viol" if culprit in cand else None

    minimal, viol, runs = ddmin(comps, test_fn, budget=100)
    assert minimal == [culprit]
    assert viol == "viol"
    assert runs == len(calls) <= 100


def test_ddmin_respects_budget():
    def never(_cand):
        return None

    comps = [("jit", (0, 1, k), 2) for k in range(64)]
    minimal, viol, runs = ddmin(comps, never, budget=10)
    assert runs <= 10 and minimal == comps and viol is None


def test_components_roundtrip():
    plan = FaultPlan(
        crashes={1: 300}, drop_bp=100, drop_seed=3, horizon_ms=5000,
        jitter_max=4, jitter_seed=2,
    )
    events = [
        ("jit", (0, 1, 5), 3),
        ("drop", (2, 0, 9), None),
        ("jit", (0, 1, 5), 3),  # duplicates collapse
    ]
    comps = plan_components(plan, events)
    assert comps == [
        ("crash", 1, 300),
        ("jit", (0, 1, 5), 3),
        ("drop", (2, 0, 9), None),
    ]
    explicit = components_plan(comps, plan.horizon_ms)
    assert explicit.crashes == {1: 300}
    assert explicit.jitter_overrides == {(0, 1, 5): 3}
    assert explicit.drop_list == ((2, 0, 9),)
    assert explicit.horizon_ms == 5000
    assert explicit.host_only() and explicit.jitter_max == 0


def test_recording_plan_records_wire_events():
    plan = RecordingPlan.of(
        FaultPlan(jitter_overrides={(0, 1, 3): 4}, horizon_ms=1000)
    )
    plan.wire(0, 1, 10, 50, 3)
    plan.wire(0, 1, 10, 50, 4)  # identity multiplier: not an event
    assert plan.events == [("jit", (0, 1, 3), 4)]


# ----------------------------------------------------------------------
# the device pipeline (slow tier: compiled fuzz runners)
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_fuzzer_catches_injected_stability_bug():
    """Regression pin for the monitors: Tempo with the stability
    threshold off by one MUST be caught within a small schedule budget,
    host-confirm through the buggy oracle twin, and shrink to a repro
    artifact of <= 10 perturbations that replays deterministically."""
    spec = FuzzSpec(
        protocol="tempo", n=3, f=1, schedules=8,
        commands_per_client=5, seed=3, inject_bug=True,
        crash_share=0.0, drop_share=0.0,
    )
    res = run_fuzz_point(spec, max_confirmations=1, shrink_budget=80)
    assert res.flagged >= 1, "monitors must catch the injected bug"
    assert res.confirmed >= 1, [
        (f.violation_cause, f.host_violation) for f in res.findings
    ]
    confirmed = [f for f in res.findings if f.confirmed]
    assert confirmed[0].violation & VIOL_ORDER, viol_names(
        confirmed[0].violation
    )
    shrunk = confirmed[0].shrunk
    assert shrunk is not None, "confirmed violations must shrink"
    assert shrunk.size <= 10, (shrunk.size, shrunk.components)
    art = confirmed[0].artifact
    assert art is not None
    # artifacts survive JSON and replay deterministically
    art = json.loads(json.dumps(art))
    rep = replay_artifact(art)
    assert rep["reproduced"], rep


@pytest.mark.slow
def test_fuzz_correct_tempo_no_violations_and_host_exact():
    """Correct Tempo over mixed jitter/crash/drop lanes: zero device
    flags, zero engine errors on non-lossy lanes, and the jitter-only
    lanes' latency results replay bit-exact through the host oracle
    (the confirmation leg of the differential contract)."""
    spec = FuzzSpec(
        protocol="tempo", n=3, f=1, schedules=12,
        commands_per_client=5, seed=5,
        crash_share=0.25, drop_share=0.25,
    )
    planet = Planet.new()
    res = run_fuzz_point(spec, planet=planet, confirm=False)
    assert res.flagged == 0, res.summary()
    bad = {
        k: v for k, v in res.engine_errors.items()
        if k not in ("requeue-livelock",)  # legitimate under drops
    }
    assert not bad, res.engine_errors

    # host-replay two jitter-only lanes bit-exact: the identical fault
    # plan drives the identical perturbed schedule on both sides
    from fantoch_tpu.mc.fuzz import draw_plans as _dp
    from fantoch_tpu.engine.protocols import dev_protocol

    config = Config(**dev_config_kwargs("tempo", 3, 1))
    dev = dev_protocol("tempo", 3, keys=4)
    plans = _dp(spec, config, dev)
    jitter_only = [p for p in plans if not p.crashes and not p.drop_bp]
    assert jitter_only, "mix must contain jitter-only lanes"
    for plan in jitter_only[:2]:
        violation, _ = host_check(spec, plan, planet=planet)
        assert violation is None, violation


@pytest.mark.slow
def test_fuzz_basic_count_monitoring():
    """Basic (order monitoring off — its executor guarantees none):
    the exactly-once counters still run clean across a jittered batch."""
    spec = FuzzSpec(
        protocol="basic", n=3, f=1, schedules=6,
        commands_per_client=5, seed=1,
        crash_share=0.0, drop_share=0.0,
    )
    res = run_fuzz_point(spec, confirm=False)
    assert res.flagged == 0, res.summary()
    assert not res.engine_errors, res.engine_errors

"""Pipelined sweep segments + buffer donation + dtype narrowing
(parallel/pipeline.py, run_sweep(pipeline_depth=, narrow=),
engine/core.py build_segment_runner(donate=, narrow=)).

The contracts under test:

* pipelined dispatch (K segments in flight, liveness resolved on slot
  reuse) produces **byte-identical** ``LaneResults`` to the serial
  reference loop (``pipeline_depth=1``) — speculative segments past
  the batch's end are fixed-point no-ops;
* the dtype-narrowing pass (i16/i8 storage planes widened inside the
  step) is invisible in results — ``narrow=True`` ≡ ``narrow=False``
  byte-for-byte — and actually narrows something at the test shapes;
* the segment runner really donates its input state (the buffer is
  consumed, no silent fallback copy);
* a checkpoint written under pipelining resumes bit-exactly (under
  either depth), loses at most the in-flight window, and a narrowing
  disagreement between writer and resumer is refused by name.

Tier-1 pins tempo + basic; the full protocol matrix × both shard
paths rides in the slow tier.

Every sweep here runs ``scan_window=1`` — this file is the *segment-
loop* reference suite (per-segment dispatch, per-segment liveness,
segment-granular checkpoint cadence). The scan-fused window path that
replaces it as the production default is pinned against these same
contracts in tests/test_scan_window.py.
"""

import json

import numpy as np
import pytest

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.checkpoint import (
    CheckpointMismatchError,
    CheckpointSpec,
    SweepInterrupted,
    checkpoint_exists,
)
from fantoch_tpu.engine.protocols import (
    dev_config_kwargs,
    dev_protocol,
    partial_dev_protocol,
)
from fantoch_tpu.engine.spec import narrow_spec
from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep
from fantoch_tpu.registry import DEV_PROTOCOLS, PARTIAL_DEV_PROTOCOLS

COMMANDS = 2
SEG = 8  # segments small enough that every lane spans several


def _blob(results) -> str:
    return json.dumps([r.to_json() for r in results], sort_keys=True)


def _specs(name: str, conflicts=(0, 100), subsets=4, shards=1):
    planet = Planet.new()
    regions = planet.regions()
    clients = 3
    pool = 1
    total = COMMANDS * clients
    if shards > 1:
        pool = 4
        dev = partial_dev_protocol(name, clients, shards, pool_size=pool)
        dims = EngineDims.for_partial(dev, 3, clients, total, regions=3)
        base = Config(
            **dev_config_kwargs(name, 3, 1),
            shard_count=shards,
            executor_executed_notification_interval_ms=100,
            executor_cleanup_interval_ms=100,
        )
    else:
        dev = dev_protocol(name, clients)
        dims = EngineDims.for_protocol(
            dev, n=3, clients=clients, payload=dev.payload_width(3),
            total_commands=total, dot_slots=total + 1, regions=3,
        )
        base = Config(**dev_config_kwargs(name, 3, 1))
    specs = make_sweep_specs(
        dev,
        planet,
        region_sets=[regions[i : i + 3] for i in range(subsets)],
        fs=[1],
        conflicts=list(conflicts),
        commands_per_client=COMMANDS,
        clients_per_region=1,
        dims=dims,
        config_base=base,
        pool_size=pool,
    )
    return dev, dims, specs


# ----------------------------------------------------------------------
# narrow-spec unit behavior (host only)
# ----------------------------------------------------------------------


def test_narrow_spec_bounds_pick_storage_dtypes():
    dev, _dims, _specs_ = _specs("basic", subsets=1)
    # tiny budgets: every candidate plane narrows to i8
    ctx = {"cmd_budget": np.full((4, 3), 2, np.int32)}
    spec = dict(narrow_spec(dev, ctx))
    assert spec["clients/issued"] == "int8"
    assert spec["metrics/hist"] == "int8"
    assert spec["ps/m_fast_path"] == "int8"
    assert spec["ps/m_stable"] == "int8"
    # mid-size budgets: per-client counters fit i16 (2x headroom) but
    # the lane total (3 x 12000, doubled) passes the i16 range, so the
    # completion-count planes stay wide
    ctx = {"cmd_budget": np.full((4, 3), 12_000, np.int32)}
    spec = dict(narrow_spec(dev, ctx))
    assert spec["clients/issued"] == "int16"
    assert "metrics/hist" not in spec
    assert "ps/m_stable" not in spec
    # budgets past the i16 range (with headroom) keep every counter
    # wide; only the budget-independent parts plane (bound = max cmd
    # parts, 1 on single-shard lanes) still narrows
    ctx = {"cmd_budget": np.full((4, 3), 20_000, np.int32)}
    assert dict(narrow_spec(dev, ctx)) == {"clients/parts": "int8"}


# ----------------------------------------------------------------------
# pipelined ≡ serial, narrowed ≡ wide (tier-1: tempo + basic)
# ----------------------------------------------------------------------


@pytest.mark.parametrize("name", ["basic", "tempo"])
def test_pipelined_and_narrowed_match_serial(name):
    dev, dims, specs = _specs(name)
    serial = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=1
    )
    ref = _blob(serial)
    assert serial[0].completed == COMMANDS * 3 and not serial[0].err
    for depth in (2, 3):
        piped = run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=depth
        )
        assert _blob(piped) == ref, f"pipeline_depth={depth} diverged"
    wide = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=2,
        narrow=False,
    )
    assert _blob(wide) == ref, "narrow=False diverged"


# ----------------------------------------------------------------------
# donation: the input state buffer is consumed, never fallback-copied
# ----------------------------------------------------------------------

# Donation and the persistent compile cache are mutually exclusive on
# the pinned jaxlib (engine/core.py donation_safe — now a VERSION
# GATE: the exclusion retires itself at DONATION_CACHE_FIX_JAXLIB; it
# was re-confirmed real on this pin while building the AOT path): a
# warm-cache process running a donated executable flakily corrupts the
# aliased state. This pytest process enables the cache (conftest), so
# the donated path is exercised in a CACHE-FREE SUBPROCESS — exactly
# how a donation-safe production process would run it. The donated
# run below uses the default scan window, so it doubles as a
# donated-windowed ≡ undonated-serial cross-flavor identity pin.
_DONATION_SCRIPT = r"""
import json
import warnings

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.core import (
    cast_state_planes,
    donation_safe,
    init_lane_state,
)
from fantoch_tpu.engine.faults import NO_FAULTS
from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol
from fantoch_tpu.engine.spec import narrow_spec, stack_lanes
from fantoch_tpu.parallel.sweep import (
    _cached_runner,
    make_sweep_specs,
    run_sweep,
)

assert donation_safe(), "cache-free subprocess must be donation-safe"

planet = Planet.new()
regions = planet.regions()
clients = 3
COMMANDS = 2
dev = dev_protocol("basic", clients)
total = COMMANDS * clients
dims = EngineDims.for_protocol(
    dev, n=3, clients=clients, payload=dev.payload_width(3),
    total_commands=total, dot_slots=total + 1, regions=3,
)
base = Config(**dev_config_kwargs("basic", 3, 1))
specs = make_sweep_specs(
    dev, planet, region_sets=[regions[i:i + 3] for i in range(4)],
    fs=[1], conflicts=[0, 100], commands_per_client=COMMANDS,
    clients_per_region=1, dims=dims, config_base=base,
)

# 1) the donated runner really consumes its input (no fallback copy)
ctx = stack_lanes(specs)
nspec = narrow_spec(dev, ctx)
assert nspec, "test shape must actually narrow something"
states = [init_lane_state(dev, dims, s.ctx) for s in specs]
state = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *states)
state = cast_state_planes(state, nspec, store=True)
mesh = Mesh(np.asarray(jax.devices()), ("sweep",))
sharding = NamedSharding(mesh, PartitionSpec("sweep"))
put = lambda t: jax.tree_util.tree_map(
    lambda a: jax.device_put(a, sharding), t
)
state, ctx = put(state), put(ctx)
probe = state["pool"]
assert str(state["metrics"]["hist"].dtype) == "int8", "storage dtype"
runner, _alive = _cached_runner(
    dev, dims, 1 << 22, False, NO_FAULTS, 0, nspec, True
)
with warnings.catch_warnings(record=True) as caught:
    warnings.simplefilter("always")
    out, _running = runner(state, ctx, np.int32(8))
    jax.block_until_ready(out)
bad = [str(w.message) for w in caught if "donat" in str(w.message).lower()]
assert not bad, f"donation fell back to a copy: {bad}"
assert probe.is_deleted(), "input state survived the segment call"
assert str(out["metrics"]["hist"].dtype) == "int8"

# 2) donated + pipelined + narrowed run_sweep == undonated serial,
#    byte for byte
blob = lambda rs: json.dumps([r.to_json() for r in rs], sort_keys=True)
donated = run_sweep(dev, dims, specs, segment_steps=8, pipeline_depth=2)
import os
os.environ["FANTOCH_SWEEP_DONATE"] = "0"
undonated = run_sweep(dev, dims, specs, segment_steps=8, pipeline_depth=1, scan_window=1)
assert blob(donated) == blob(undonated), "donated path diverged"
assert donated[0].completed == COMMANDS * 3 and not donated[0].err
print("DONATION-OK")
"""


def test_segment_runner_donates_state_cache_free_subprocess():
    import os
    import subprocess
    import sys

    import fantoch_tpu

    repo = os.path.dirname(os.path.dirname(fantoch_tpu.__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # no enable_compile_cache in the child and no cache env: the
    # process stays cache-free, so donation_safe() engages
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("FANTOCH_SWEEP_DONATE", None)
    if "xla_force_host_platform_device_count" not in env.get(
        "XLA_FLAGS", ""
    ):
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    out = subprocess.run(
        [sys.executable, "-c", _DONATION_SCRIPT],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    assert "DONATION-OK" in out.stdout, (out.stdout, out.stderr[-2000:])


# ----------------------------------------------------------------------
# checkpoint under pipelining
# ----------------------------------------------------------------------


def test_checkpoint_under_pipeline_resumes_bit_exact(tmp_path):
    dev, dims, specs = _specs("basic")
    control = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=1
    )
    ck = str(tmp_path / "ck")
    # kill (deterministically) mid-window: stop after ONE counted
    # segment while a second rides in flight (depth 2). The save drains
    # the window first, so the artifact records a determinate boundary…
    with pytest.raises(SweepInterrupted) as e:
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=2,
            checkpoint=CheckpointSpec(path=ck, stop_after_segments=1),
        )
    assert e.value.reason == "segment-limit"
    assert checkpoint_exists(ck)
    # …and loses at most the in-flight window: the saved boundary is
    # within pipeline_depth segments of the stop point
    until = e.value.until
    assert until <= 2 * SEG, until
    # resume under the OTHER depth — drained boundaries are depth-
    # agnostic, so checkpoints interchange freely
    resumed = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=3,
        checkpoint=CheckpointSpec(path=ck),
    )
    assert not checkpoint_exists(ck)
    assert _blob(resumed) == _blob(control)


def test_narrowing_disagreement_refused_by_name(tmp_path):
    dev, dims, specs = _specs("basic")
    ck = str(tmp_path / "ck")
    with pytest.raises(SweepInterrupted):
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1,
            checkpoint=CheckpointSpec(path=ck, stop_after_segments=1),
        )
    # a narrow-saved checkpoint must not resume into an un-narrowed
    # runner (the saved planes are i8/i16; the carry would mismatch) —
    # refusal, by name, not a trace error
    with pytest.raises(CheckpointMismatchError, match="narrow"):
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1, narrow=False,
            checkpoint=CheckpointSpec(path=ck),
        )


# ----------------------------------------------------------------------
# double-buffered checkpoint saves (parallel/pipeline.py
# CheckpointBuffer): the save's fetch + write overlap the next
# in-flight window, with the artifact bytes unchanged
# ----------------------------------------------------------------------


def test_checkpoint_buffer_parks_exact_boundary_bytes():
    """The deferred fetch returns the PARKED boundary state even after
    later segments were dispatched on top of it — undonated inputs are
    immutable, so overlap can never save a moved-on state."""
    import jax

    from fantoch_tpu.parallel.pipeline import CheckpointBuffer

    state0 = {
        "a": jax.device_put(np.arange(8, dtype=np.int32)),
        "nested": {"b": jax.device_put(np.ones((4, 4), np.float32))},
    }
    step = jax.jit(
        lambda s: {
            "a": s["a"] + 1,
            "nested": {"b": s["nested"]["b"] * 2.0},
        }
    )
    direct = jax.device_get(state0)

    buf = CheckpointBuffer()
    assert not buf.pending
    buf.begin(state0, until=8)
    assert buf.pending
    s1 = step(state0)
    s2 = step(s1)  # two "segments" in flight past the boundary
    saved = {}
    assert buf.flush(
        lambda host, until: saved.update(state=host, until=until)
    )
    assert saved["until"] == 8
    np.testing.assert_array_equal(saved["state"]["a"], direct["a"])
    np.testing.assert_array_equal(
        saved["state"]["nested"]["b"], direct["nested"]["b"]
    )
    assert not buf.pending
    assert buf.flush(lambda *_: None) is False  # idempotent no-op
    del s1, s2


def test_overlapped_saves_resume_bit_exact(tmp_path):
    """every=1 defers a save at EVERY boundary before the stop (the
    stopping save itself is synchronous — SweepInterrupted must raise
    with the state already durable); resuming the artifact reproduces
    the uninterrupted control byte-for-byte."""
    dev, dims, specs = _specs("basic")
    control = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=1
    )
    ck = str(tmp_path / "ck")
    with pytest.raises(SweepInterrupted) as e:
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=2,
            checkpoint=CheckpointSpec(
                path=ck, every=1, stop_after_segments=3
            ),
        )
    assert e.value.reason == "segment-limit"
    assert checkpoint_exists(ck)
    resumed = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1,
        checkpoint=CheckpointSpec(path=ck),
    )
    assert _blob(resumed) == _blob(control)


def test_deferred_saves_land_on_determinate_boundaries(tmp_path):
    """Kept final artifacts from depth-1 and depth-3 runs of the same
    grid carry the SAME payload hash: deferred saves happen on drained
    boundaries whose states depend only on the (deterministic) segment
    ladder, never on dispatch overlap or flag-resolution timing."""
    import json as _json

    dev, dims, specs = _specs("basic")
    shas = []
    for depth, name in ((1, "k1"), (3, "k3")):
        ck = str(tmp_path / name)
        run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=depth,
            checkpoint=CheckpointSpec(path=ck, every=1, keep=True),
        )
        manifest = _json.load(open(str(tmp_path / name / "manifest.json")))
        shas.append((manifest["meta"]["until"],
                     manifest["payload_sha256"]))
    assert shas[0] == shas[1], shas


# ----------------------------------------------------------------------
# the full matrix (slow tier: compiles)
# ----------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("shard", [False, True])
@pytest.mark.parametrize("name", DEV_PROTOCOLS)
def test_pipelined_matches_serial_full_protocols(name, shard):
    dev, dims, specs = _specs(name, subsets=2)
    serial = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=1,
        shard_lanes=shard,
    )
    for depth in (2, 3):
        piped = run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=depth,
            shard_lanes=shard,
        )
        assert _blob(piped) == _blob(serial), (name, shard, depth)


@pytest.mark.slow
@pytest.mark.parametrize("shard", [False, True])
@pytest.mark.parametrize("name", PARTIAL_DEV_PROTOCOLS)
def test_pipelined_matches_serial_partial_twins(name, shard):
    dev, dims, specs = _specs(name, conflicts=(50, 100), subsets=2,
                              shards=2)
    serial = run_sweep(
        dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=1,
        shard_lanes=shard,
    )
    for depth in (2, 3):
        piped = run_sweep(
            dev, dims, specs, segment_steps=SEG, scan_window=1, pipeline_depth=depth,
            shard_lanes=shard,
        )
        assert _blob(piped) == _blob(serial), (name, shard, depth)

"""Dot-window recycling under sustained load, in-suite.

tools/stress.py's full shape (BASELINE config 5: ~100k commands) is a
device run; this CPU-sized shape keeps the property the small diff
tests never touch — the per-source dot window turning over many times
(submits per source ≫ dot_slots) with GC racing the recycling — so the
recycling path has coverage on every suite run (VERDICT r2 weak #6).
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.stress import run_stress  # noqa: E402


def _recycling(n, commands, dot_slots, min_turnover):
    report = run_stress(
        n=n,
        commands=commands,
        clients_per_region=2,
        dot_slots=dot_slots,
        pool=2048,
        segment_steps=1 << 14,
    )
    assert report["err"] == "ok"
    assert report["completed"] == report["commands"]
    # the property under test: every source recycled its window
    submits_per_source = report["commands"] / n
    assert submits_per_source > min_turnover * dot_slots


def test_stress_smoke_dot_window_recycling():
    """Every-suite-run smoke: the window still turns over ~10x per
    source, at a scale that keeps the default tier fast."""
    _recycling(n=3, commands=500, dot_slots=16, min_turnover=8)


@pytest.mark.slow
def test_stress_quick_dot_window_recycling():
    _recycling(n=5, commands=2500, dot_slots=64, min_turnover=4)

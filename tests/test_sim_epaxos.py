"""EPaxos whole-protocol simulation tests.

Mirrors fantoch_ps/src/protocol/mod.rs sim_epaxos_* tests: fast path
requires all fast-quorum deps equal, which holds trivially for n=3
(fast quorum = 2, only the coordinator's deps are echoed back) and fails
sometimes under conflicts for n=5.
"""

from fantoch_tpu.core import Config
from fantoch_tpu.protocol import EPaxos

from harness import sim_test


def test_sim_epaxos_3_1():
    assert sim_test(EPaxos, Config(n=3, f=1)) == 0


def test_sim_epaxos_5_2():
    assert sim_test(EPaxos, Config(n=5, f=2)) > 0

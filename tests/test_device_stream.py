"""Intermediate-conflict differential tests via DeviceStream.

Round-1 weakness: diff tests pinned conflict to {0, 100} because the
oracle (python ``random``) and the engine (counter-based threefry) drew
different key streams. ``DeviceStream`` replays the engine's stream
host-side, so every conflict rate cross-validates exactly.
"""

import pytest

from fantoch_tpu.client import DeviceStream, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.protocols import EPaxosDev, TempoDev
from fantoch_tpu.protocol import EPaxos, Tempo
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

COMMANDS = 30
CPR = 1


def run_pair(oracle_cls, dev, config, conflict, zipf=None):
    n = config.n
    planet = Planet.new()
    regions = planet.regions()[:n]
    clients = CPR * n
    wl = Workload(
        shard_count=1,
        key_gen=DeviceStream(conflict_rate=conflict, pool_size=1, zipf=zipf),
        keys_per_command=1,
        commands_per_client=COMMANDS,
        payload_size=0,
    )
    runner = Runner(
        oracle_cls, planet, config, wl, CPR, regions, list(regions)
    )
    metrics, _, lat = runner.run(extra_sim_time_ms=1000)
    fast = slow = 0
    for pm, _em in metrics.values():
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0

    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev,
        n=n,
        clients=clients,
        payload=dev.payload_width(n),
        total_commands=total,
        dot_slots=total + 1,
        regions=n,
    )
    spec = make_lane(
        dev,
        planet,
        config,
        conflict_rate=conflict,
        pool_size=1,
        zipf=zipf,
        commands_per_client=COMMANDS,
        clients_per_region=CPR,
        process_regions=regions,
        client_regions=regions,
        dims=dims,
    )
    res = run_lanes(dev, dims, [spec])[0]
    return regions, lat, fast, slow, res


@pytest.mark.parametrize("conflict", [10, 50])
def test_tempo_intermediate_conflict_exact(conflict):
    config = Config(
        n=3, f=1, gc_interval_ms=100, tempo_detached_send_interval_ms=100
    )
    clients = CPR * config.n
    dev = TempoDev(keys=1 + clients)
    regions, lat, fast, slow, res = run_pair(Tempo, dev, config, conflict)
    assert res.err == 0, res.err_cause
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    for region in regions:
        assert res.latency_mean(region) == lat[region][1].mean(), region


def test_epaxos_zipf_exact():
    """Zipf workload cross-validation (device zipf vs oracle zipf from
    the same stream) — the device zipf path was round 1's breakage."""
    config = Config(n=3, f=1, gc_interval_ms=100)
    clients = CPR * config.n
    dev = EPaxosDev(keys=64)
    regions, lat, fast, slow, res = run_pair(
        EPaxos, dev, config, conflict=0, zipf=(0.9, 64)
    )
    assert res.err == 0, res.err_cause
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    for region in regions:
        assert res.latency_mean(region) == lat[region][1].mean(), region

"""Determinism-family tests (fantoch_tpu/lint/determinism.py +
ordering.py): GL401 unordered-source/ordered-sink taxonomy units on
synthetic sources (including the sorted-at-source clean case and the
membership-only non-finding), GL402/GL403/GL404 units, the ledger
regression gate (new id, count bump, reasonless baseline entry),
clean-at-HEAD + ledger≡baseline pins, canonical_json byte-identity,
the seeded CI self-checks, baseline cross-pollination guards, and the
scan-set coverage self-tests — all pure AST, no device and no
tracing."""

import json
import os
import textwrap

import pytest

from fantoch_tpu.lint.determinism import (
    DEFAULT_DETERMINISM_BASELINE,
    gate_ledger,
    ledger_summary,
    load_determinism_baseline,
    run_determinism,
    run_determinism_selfcheck,
    scan_determinism,
    write_determinism_baseline,
)
from fantoch_tpu.registry import DETERMINISM_SCAN_PATHS


def _scan(tmp_path, src, name="synth.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(src))
    return scan_determinism([str(path)])


def _sites(tmp_path, src):
    sites, findings = _scan(tmp_path, src)
    assert findings == [], [f.render() for f in findings]
    return sites


def _kinds(sites, rule):
    return sorted(s.kind for s in sites if s.rule == rule)


# ----------------------------------------------------------------------
# GL401: unordered-source taxonomy
# ----------------------------------------------------------------------


def test_listdir_iteration_flags(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import os
        def merge(path):
            out = []
            for name in os.listdir(path):
                out.append(name)
            return out
        """,
    )
    assert _kinds(sites, "GL401") == ["iter-listdir"]


def test_sorted_at_source_is_clean(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import os, glob
        def merge(path):
            out = []
            for name in sorted(os.listdir(path)):
                out.append(name)
            for p in sorted(glob.glob(path + "/*.json")):
                out.append(p)
            return out
        """,
    )
    assert _kinds(sites, "GL401") == []


def test_set_iteration_flags(tmp_path):
    sites = _sites(
        tmp_path,
        """
        def rank(results):
            winners = {r for r in results}
            order = []
            for w in winners:
                order.append(w)
            return order
        """,
    )
    assert _kinds(sites, "GL401") == ["iter-set"]


def test_set_membership_only_is_clean(tmp_path):
    # sets used purely for O(1) membership never expose iteration
    # order — the required non-finding
    sites = _sites(
        tmp_path,
        """
        def missing(units, results):
            seen = set(r["unit"] for r in results)
            return [u for u in units if u not in seen]
        """,
    )
    assert _kinds(sites, "GL401") == []


def test_tainted_name_iteration_flags(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import os
        def scan(path):
            names = os.listdir(path)
            return [n for n in names]
        """,
    )
    assert _kinds(sites, "GL401") == ["iter-listdir"]


def test_sorted_launders_the_name(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import os
        def scan(path):
            names = os.listdir(path)
            names = sorted(names)
            return [n for n in names]
        """,
    )
    assert _kinds(sites, "GL401") == []


def test_glob_scandir_iterdir_flag(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import glob, os, pathlib
        def scan(path):
            a = [p for p in glob.glob(path)]
            b = [e for e in os.scandir(path)]
            c = [f for f in pathlib.Path(path).iterdir()]
            return a, b, c
        """,
    )
    assert _kinds(sites, "GL401") == [
        "iter-glob", "iter-iterdir", "iter-scandir",
    ]


def test_materializing_a_set_flags(tmp_path):
    sites = _sites(
        tmp_path,
        """
        def rank(points):
            winners = set(points)
            return list(winners), ",".join(winners)
        """,
    )
    assert _kinds(sites, "GL401") == ["iter-set", "iter-set"]


def test_sorted_consumer_suppresses_inner_generator(tmp_path):
    # sorted(f(x) for x in s): the set is iterated, but the consumer
    # re-orders — order-free overall
    sites = _sites(
        tmp_path,
        """
        def rank(points):
            winners = set(points)
            return sorted(w + 1 for w in winners)
        """,
    )
    assert _kinds(sites, "GL401") == []


def test_dict_views_of_tainted_dict_flag(tmp_path):
    sites = _sites(
        tmp_path,
        """
        def views(results):
            winners = set(results)
            return [w for w in winners.copy()]
        """,
    )
    assert _kinds(sites, "GL401") == ["iter-set"]


# ----------------------------------------------------------------------
# GL402: PRNG discipline
# ----------------------------------------------------------------------


def test_wall_clock_into_journal_flags(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import json, time
        def entry(fh, unit):
            rec = {"unit": unit, "at": time.time()}
            fh.write(json.dumps(rec, sort_keys=True))
        """,
    )
    assert _kinds(sites, "GL402") == ["time.time"]


def test_perf_counter_is_not_a_source(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import json, time
        def entry(fh, unit, t0):
            rec = {"unit": unit, "elapsed": time.perf_counter() - t0}
            fh.write(json.dumps(rec, sort_keys=True))
        """,
    )
    assert _kinds(sites, "GL402") == []


def test_seeded_stream_is_clean(tmp_path):
    # random.Random(seed) / np.random.default_rng(seed) are the
    # journaled-stream discipline — not sources
    sites = _sites(
        tmp_path,
        """
        import json, random
        import numpy as np
        def plan(fh, seed, n):
            rng = random.Random(seed)
            g = np.random.default_rng(seed)
            rec = {"plan": [rng.randint(0, 7) for _ in range(n)],
                   "x": float(g.uniform())}
            fh.write(json.dumps(rec, sort_keys=True))
        """,
    )
    assert _kinds(sites, "GL402") == []


def test_default_stream_random_flags(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import json, random
        import numpy as np
        def plan(fh, n):
            rec = {"plan": [random.randint(0, 7) for _ in range(n)],
                   "x": float(np.random.uniform())}
            fh.write(json.dumps(rec, sort_keys=True))
        """,
    )
    assert _kinds(sites, "GL402") == ["np.random", "random"]


def test_pid_derived_filename_flags(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import os
        def write(path, data):
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "a") as fh:
                fh.write(data)
        """,
    )
    assert _kinds(sites, "GL402") == ["os.getpid"]


def test_uuid_flags_and_bare_ttl_compare_is_clean(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import json, time, uuid
        def name(fh):
            fh.write(json.dumps({"id": str(uuid.uuid4())},
                                sort_keys=True))
        def expired(mtime, ttl):
            now = time.time()
            return now - mtime > ttl
        """,
    )
    assert _kinds(sites, "GL402") == ["uuid"]


# ----------------------------------------------------------------------
# GL403: canonical serialization
# ----------------------------------------------------------------------


def test_json_dump_without_sort_keys_flags(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import json
        def save(path, obj):
            with open(path, "a") as fh:
                json.dump(obj, fh, indent=2)
        """,
    )
    assert _kinds(sites, "GL403") == ["dump-unsorted"]


def test_json_dump_sorted_is_clean(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import json
        def save(path, obj):
            with open(path, "a") as fh:
                json.dump(obj, fh, indent=2, sort_keys=True)
        """,
    )
    assert _kinds(sites, "GL403") == []


def test_unsorted_dumps_reaching_write_sink_flags(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import json
        def direct(fh, obj):
            fh.write(json.dumps(obj))
        def via_name(write, obj):
            line = json.dumps(obj)
            write("x", line)
        """,
    )
    # `write` is both the fh.write attribute sink and the bare-name
    # sink in via_name
    assert _kinds(sites, "GL403") == [
        "dumps-unsorted", "dumps-unsorted",
    ]


def test_unsorted_dumps_to_stdout_is_clean(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import json
        def chatter(point):
            print(json.dumps(point))
        """,
    )
    assert _kinds(sites, "GL403") == []


def test_nonliteral_sort_keys_is_structural(tmp_path):
    sites, findings = _scan(
        tmp_path,
        """
        import json
        def save(path, obj, flag):
            with open(path, "a") as fh:
                json.dump(obj, fh, sort_keys=flag)
        """,
    )
    assert len(findings) == 1 and findings[0].rule == "GL403"
    assert "non-literal" in findings[0].message


def test_canonical_json_choke_is_sanctioned(tmp_path):
    sites = _sites(
        tmp_path,
        """
        def save(path, obj):
            from fantoch_tpu.engine.checkpoint import (
                atomic_write, canonical_json,
            )
            atomic_write(path, canonical_json(obj, indent=2))
        """,
    )
    assert _kinds(sites, "GL403") == []
    assert _kinds(sites, "GL404") == []


# ----------------------------------------------------------------------
# GL404: atomic artifacts
# ----------------------------------------------------------------------


def test_raw_writes_flag(tmp_path):
    sites = _sites(
        tmp_path,
        """
        import pathlib
        def save(path, data):
            with open(path, "w") as fh:
                fh.write(data)
        def save2(path, data):
            pathlib.Path(path).write_text(data)
        def save3(path, data):
            pathlib.Path(path).write_bytes(data)
        """,
    )
    assert _kinds(sites, "GL404") == [
        "open-w", "write-bytes", "write-text",
    ]


def test_append_and_read_modes_are_clean(tmp_path):
    # append is the sanctioned journal protocol; reads are irrelevant
    sites = _sites(
        tmp_path,
        """
        def journal(path, line):
            with open(path, "a") as fh:
                fh.write(line)
        def load(path):
            with open(path) as fh:
                return fh.read()
        def load_rb(path):
            with open(path, "rb") as fh:
                return fh.read()
        """,
    )
    assert _kinds(sites, "GL404") == []


def test_atomic_write_choke_body_is_exempt():
    # the real checkpoint.py: atomic_write's own open-for-write is the
    # sanctioned implementation, not a finding — but its pid temp name
    # stays a (baselined) GL402 site
    sites, findings = scan_determinism(
        ["fantoch_tpu/engine/checkpoint.py"]
    )
    assert findings == []
    gl404 = [s for s in sites if s.rule == "GL404"]
    assert gl404 == []
    assert any(
        s.rule == "GL402" and s.fn == "atomic_write" for s in sites
    )


# ----------------------------------------------------------------------
# ledger gate
# ----------------------------------------------------------------------


def _synthetic_sites(tmp_path):
    return _sites(
        tmp_path,
        """
        import os
        def scan(path):
            return [n for n in os.listdir(path)]
        """,
    )


def test_gate_new_id_is_a_finding(tmp_path):
    sites = _synthetic_sites(tmp_path)
    findings, stale = gate_ledger(sites, {})
    assert len(findings) == 1
    assert findings[0].rule == "GL401"
    assert "NEW determinism hazard" in findings[0].message
    assert stale == []


def test_gate_baselined_site_passes_and_count_bump_fails(tmp_path):
    sites = _synthetic_sites(tmp_path)
    fid = sites[0].id
    base = {fid: {"count": 1, "reason": "synthetic justification"}}
    findings, _ = gate_ledger(sites, base)
    assert findings == []
    findings, _ = gate_ledger(sites + sites, base)
    assert len(findings) == 1 and "count grew" in findings[0].message


def test_gate_reasonless_baseline_entry_fails(tmp_path):
    sites = _synthetic_sites(tmp_path)
    fid = sites[0].id
    findings, _ = gate_ledger(sites, {fid: {"count": 1, "reason": ""}})
    assert len(findings) == 1
    assert "no written justification" in findings[0].message
    findings, _ = gate_ledger(
        sites, {fid: {"count": 1, "reason": "UNREVIEWED placeholder"}}
    )
    assert len(findings) == 1


def test_gate_stale_allowance_is_advisory(tmp_path):
    sites = _synthetic_sites(tmp_path)
    base = {
        sites[0].id: {"count": 5, "reason": "synthetic justification"}
    }
    findings, stale = gate_ledger(sites, base)
    assert findings == []
    assert stale == [sites[0].id]


# ----------------------------------------------------------------------
# clean-at-HEAD pins
# ----------------------------------------------------------------------


def test_determinism_clean_at_head():
    findings, summary = run_determinism()
    assert findings == [], [f.render() for f in findings]
    assert summary["sites"] == summary["ids"] == summary["baseline_entries"]


def test_head_ledger_matches_checked_in_baseline():
    sites, findings = scan_determinism()
    assert findings == []
    base = load_determinism_baseline()
    assert sorted({s.id for s in sites}) == sorted(base)
    # every baselined exception carries a real written justification
    for fid, e in base.items():
        reason = str(e.get("reason", ""))
        assert reason.strip(), fid
        assert not reason.startswith("UNREVIEWED"), fid


def test_write_determinism_baseline_roundtrip(tmp_path):
    sites, _ = scan_determinism()
    path = str(tmp_path / "det.json")
    write_determinism_baseline(path, sites)
    base = load_determinism_baseline(path)
    assert sorted(base) == sorted({s.id for s in sites})
    # fresh entries get the UNREVIEWED placeholder, which the gate
    # itself then rejects — a thoughtless regen cannot go green
    findings, _ = gate_ledger(sites, base)
    assert findings and all(
        "justification" in f.message for f in findings
    )
    # a regen over reviewed entries preserves the written reasons
    reviewed = {
        fid: {"count": e["count"], "reason": f"reviewed {fid}"}
        for fid, e in base.items()
    }
    with open(path, "w") as fh:
        json.dump({"entries": reviewed}, fh)
    write_determinism_baseline(path, sites)
    base2 = load_determinism_baseline(path)
    assert all(
        base2[fid]["reason"] == f"reviewed {fid}" for fid in base2
    )


def test_canonical_json_is_byte_identical_to_sorted_dumps():
    from fantoch_tpu.engine.checkpoint import canonical_json

    obj = {"b": [1, 2], "a": {"z": 0.25, "y": None}, "c": "x"}
    assert canonical_json(obj) == json.dumps(obj, sort_keys=True)
    assert canonical_json(obj, indent=2) == json.dumps(
        obj, indent=2, sort_keys=True
    )


def test_ledger_summary_shape():
    s = ledger_summary()
    assert set(s) == {"sites", "rules", "ids"}
    assert set(s["rules"]) == {"GL401", "GL402", "GL403", "GL404"}
    assert all(isinstance(v, int) for v in s["rules"].values())
    assert s["sites"] >= s["ids"] > 0


# ----------------------------------------------------------------------
# selfchecks + CLI
# ----------------------------------------------------------------------


@pytest.mark.parametrize("kind,rule", [
    ("order", "GL401"),
    ("rng", "GL402"),
    ("json", "GL403"),
    ("write", "GL404"),
])
def test_selfcheck_fixture_names_its_rule(kind, rule):
    findings, summary = run_determinism_selfcheck(kind)
    assert findings, f"selfcheck {kind} is vacuously green"
    assert all(f.rule == rule for f in findings)
    assert summary["selfcheck_rule"] == rule


@pytest.mark.parametrize("kind,rule", [
    ("order", "GL401"),
    ("rng", "GL402"),
    ("json", "GL403"),
    ("write", "GL404"),
])
def test_cli_selfcheck_exits_nonzero_naming_rule(
    kind, rule, capsys
):
    from fantoch_tpu import cli

    with pytest.raises(SystemExit) as e:
        cli.main(["lint", "--determinism-selfcheck", kind])
    assert e.value.code == 1
    captured = capsys.readouterr()
    assert rule in captured.err
    out = json.loads(captured.out.strip().splitlines()[-1])
    assert out["selfcheck"] == kind and out["regressions"] > 0


def test_cli_determinism_only_clean_at_head(capsys):
    from fantoch_tpu import cli

    cli.main(["lint", "--determinism-only", "--baseline"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["regressions"] == 0
    assert out["determinism"]["rules"]["GL403"] == 0


def test_cli_write_determinism_baseline_refuses_narrowed_run(tmp_path):
    from fantoch_tpu import cli

    with pytest.raises(SystemExit) as e:
        cli.main([
            "lint", "--write-determinism-baseline",
            "--paths", str(tmp_path / "nope.py"),
        ])
    assert "narrowed" in str(e.value.code)


# ----------------------------------------------------------------------
# baseline cross-pollination guards (report.py write_baseline)
# ----------------------------------------------------------------------


def test_write_baseline_refuses_all_foreign_families(tmp_path):
    from fantoch_tpu.lint.report import (
        Finding, LintReport, load_baseline, write_baseline,
    )

    report = LintReport()
    report.extend([
        Finding("GL001", "tempo", "a.py:f:add", "keep"),
        Finding("GL104", "ast", "b.py:g", "keep"),
        Finding("GL201", "cost", "c.py:h:kernels", "drop"),
        Finding("GL301", "transfer", "d.py:i:bool", "drop"),
        Finding("GL404", "determinism", "e.py:j:open-w", "drop"),
    ])
    path = str(tmp_path / "baseline.json")
    write_baseline(path, report)
    base = load_baseline(path)
    assert set(base) == {
        "GL001:tempo:a.py:f:add",
        "GL104:ast:b.py:g",
    }


# ----------------------------------------------------------------------
# scan-set coverage
# ----------------------------------------------------------------------


def test_determinism_scan_paths_exist_and_exclude_lint():
    from fantoch_tpu.lint.rules import REPO_ROOT, expand_paths

    files = expand_paths(DETERMINISM_SCAN_PATHS)
    assert files, "empty determinism scan set"
    rels = [os.path.relpath(f, REPO_ROOT) for f in files]
    # the lint analyzers stay out of their own scan — except shard.py
    # and skeleton.py, whose baseline writers emit checked-in artifacts
    # and so must themselves obey the GL4xx serialization/atomicity
    # rules
    assert sorted(
        r for r in rels if r.startswith("fantoch_tpu/lint")
    ) == ["fantoch_tpu/lint/shard.py", "fantoch_tpu/lint/skeleton.py"]
    assert "fantoch_tpu/cli.py" in rels
    assert any(r.startswith("fantoch_tpu/campaign") for r in rels)
    assert any(r.startswith("fantoch_tpu/fleet") for r in rels)
    assert any(r.startswith("fantoch_tpu/mc") for r in rels)
    assert any(r.startswith("fantoch_tpu/bote") for r in rels)


def test_uncovered_traced_modules_still_empty():
    from fantoch_tpu.lint.rules import uncovered_traced_modules

    assert uncovered_traced_modules() == []


def test_determinism_baseline_is_checked_in():
    assert os.path.exists(DEFAULT_DETERMINISM_BASELINE)

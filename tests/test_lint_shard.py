"""The GL501-GL503 shardability family (lint/shard.py +
parallel/specs.py + the run_sweep state-proof consult): taint-rule
units over synthetic jaxprs, the ledger gate's refusal semantics, the
clean-at-HEAD pins against the checked-in ``lint/shard_baseline.json``,
the GL502 partition-rule auditor, ``StateShardingError`` wiring, and
the empirical pin the whole family exists for — a GL502-proven
PartitionSpec for tempo's N axis driving a ``shard_map`` run
bit-identical to the single-device reference on the 8-device CPU
mesh."""

import json
import os

import numpy as np
import pytest

from fantoch_tpu.lint.report import Finding
from fantoch_tpu.lint.shard import (
    CHOKE_FNS,
    COLLECTIVE,
    DEFAULT_SHARD_BASELINE,
    REPLICATED,
    SHARDABLE,
    _make_axis_taint,
    _verdict,
    audit_partition_rules,
    gate_shard_ledger,
    load_shard_baseline,
    run_shard,
    run_shard_selfcheck,
    shard_axis_ledger_summary,
)
from fantoch_tpu.registry import DEV_PROTOCOLS, PARTIAL_DEV_PROTOCOLS

ALL_AUDITS = tuple(DEV_PROTOCOLS) + tuple(
    f"{n}@2shards" for n in PARTIAL_DEV_PROTOCOLS
)


# ----------------------------------------------------------------------
# GL501 taint-rule units (synthetic jaxprs)
# ----------------------------------------------------------------------


def _taint_events(fn, args, axis, axis_size):
    """Run one AxisTaint pass over ``fn``'s jaxpr with the taint
    seeded on ``axis`` of the first argument."""
    import jax

    from fantoch_tpu.lint.jaxpr import flatten_jaxpr

    closed = jax.make_jaxpr(fn)(*args)
    flat, invars, _outvars = flatten_jaxpr(closed)
    AxisTaint = _make_axis_taint()
    ana = AxisTaint(flat, "unit", axis_size, CHOKE_FNS)
    ana.env[invars[0]] = axis
    ana.run()
    return ana.events


def test_cross_axis_reduce_is_replicated():
    import jax.numpy as jnp

    x = np.zeros((4, 3), np.float32)
    events = _taint_events(lambda x: jnp.sum(x, axis=0), (x,), 0, 4)
    verdict, reason = _verdict(events)
    assert verdict == REPLICATED
    assert "reduce_sum" in reason


def test_cross_axis_gather_is_replicated():
    import jax.numpy as jnp

    x = np.zeros((4, 3), np.float32)
    idx = np.array([1, 0, 3, 2], np.int32)
    events = _taint_events(
        lambda x: jnp.take(x, jnp.asarray(idx), axis=0), (x,), 0, 4
    )
    verdict, _reason = _verdict(events)
    assert verdict == REPLICATED


def test_choke_point_mixing_is_collective():
    import jax.numpy as jnp

    # the frame NAME is the trust boundary: the same reduce inside a
    # declared choke function classifies COLLECTIVE, not REPLICATED
    def frontier_min(x):
        return jnp.min(x, axis=0)

    assert "frontier_min" in CHOKE_FNS
    x = np.zeros((4, 3), np.float32)
    events = _taint_events(
        lambda x: frontier_min(x * 2) + 1.0, (x,), 0, 4
    )
    verdict, reason = _verdict(events)
    assert verdict == COLLECTIVE
    assert "frontier_min" in reason
    # and post-choke values are re-replicated: no later event fired
    assert all(kind == "collective" for kind, _e, _w in events)


def test_elementwise_and_off_axis_scan_are_shardable():
    import jax
    import jax.numpy as jnp

    x = np.zeros((4, 3), np.float32)
    verdict, _ = _verdict(
        _taint_events(lambda x: x * 2.0 + 1.0, (x,), 0, 4)
    )
    assert verdict == SHARDABLE

    # a scan over the OTHER axis slices only untainted positions; the
    # carry stays per-position along the tainted axis
    def scanned(x):
        def body(c, row):
            return c + row, row * 2.0

        return jax.lax.scan(body, jnp.zeros_like(x[:, 0]), x.T)

    verdict, _ = _verdict(_taint_events(scanned, (x,), 0, 4))
    assert verdict == SHARDABLE


# ----------------------------------------------------------------------
# GL501 ledger gate units
# ----------------------------------------------------------------------

_ENT = {"verdict": SHARDABLE, "reason": "synthetic evidence"}


def test_gate_missing_audit_ledger_is_a_finding():
    findings, stale = gate_shard_ledger("tempo", {"p:0:N": _ENT}, {})
    assert len(findings) == 1 and findings[0].rule == "GL501"
    assert "no axis ledger" in findings[0].message
    assert stale == []


def test_gate_new_pair_and_verdict_change_fail():
    base = {"ledgers": {"tempo": {"p:0:N": dict(_ENT)}}}
    findings, _ = gate_shard_ledger(
        "tempo", {"p:0:N": dict(_ENT), "q:0:N": dict(_ENT)}, base
    )
    assert len(findings) == 1 and "NEW axis pair" in findings[0].message

    # a change in EITHER direction fails — upgrades are regenerated
    # deliberately, never absorbed
    up = {"p:0:N": {"verdict": REPLICATED, "reason": "x"}}
    findings, _ = gate_shard_ledger("tempo", up, base)
    assert len(findings) == 1 and "verdict changed" in findings[0].message
    base2 = {
        "ledgers": {"tempo": {"p:0:N": {"verdict": REPLICATED,
                                        "reason": "x"}}}
    }
    findings, _ = gate_shard_ledger("tempo", {"p:0:N": dict(_ENT)}, base2)
    assert len(findings) == 1 and "verdict changed" in findings[0].message


def test_gate_reasonless_entry_fails_and_stale_is_advisory():
    base = {
        "ledgers": {
            "tempo": {
                "p:0:N": {"verdict": SHARDABLE, "reason": ""},
                "gone:0:N": dict(_ENT),
            }
        }
    }
    findings, stale = gate_shard_ledger(
        "tempo", {"p:0:N": dict(_ENT)}, base
    )
    assert len(findings) == 1 and "no evidence reason" in findings[0].message
    assert stale == ["gone:0:N"]

    # UNREVIEWED placeholders (a thoughtless regen) also fail
    base["ledgers"]["tempo"]["p:0:N"]["reason"] = "UNREVIEWED todo"
    findings, _ = gate_shard_ledger("tempo", {"p:0:N": dict(_ENT)}, base)
    assert any("no evidence reason" in f.message for f in findings)


# ----------------------------------------------------------------------
# GL502 partition-rule auditor units
# ----------------------------------------------------------------------


def _p(*parts):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*parts)


_SYN_ENTRIES = {
    "state.ps.clock:0:N": {"verdict": COLLECTIVE, "reason": "r"},
    "state.spine:0:N": {"verdict": REPLICATED, "reason": "r"},
}


def test_rule_sharding_replicated_axis_refused():
    findings = audit_partition_rules(
        "tempo",
        _SYN_ENTRIES,
        [(r"", _p("lanes", "state"))],
    )
    assert [f.rule for f in findings] == ["GL502"]
    assert "REPLICATED" in findings[0].message
    assert "state.spine" in findings[0].id


def test_rule_sharding_unverdicted_axis_refused():
    findings = audit_partition_rules(
        "tempo",
        _SYN_ENTRIES,
        [(r"", _p("lanes", None, "state"))],
        planes=["state.ps.clock", "state.spine", "ctx.scalar"],
    )
    # no plane has a verdict at axis 1, and ctx.scalar has none at all
    assert findings and all(f.rule == "GL502" for f in findings)
    assert any("NO GL501 verdict" in f.message for f in findings)


def test_dead_rule_and_bad_mesh_axes_refused():
    findings = audit_partition_rules(
        "tempo",
        _SYN_ENTRIES,
        [
            (r"^state\.nope\.", _p("lanes", "state")),
            (r"^state\.ps\.", _p("state")),
            (r"", _p("lanes", "model")),
        ],
    )
    rules_hit = sorted(f.message.split("—")[0] for f in findings)
    assert any("dead partition rule" in m for m in rules_hit)
    assert any("leading dimension" in f.message for f in findings)
    assert any("unsupported mesh axis" in f.message for f in findings)
    assert all(f.rule == "GL502" for f in findings)


def test_unmatched_plane_refused():
    findings = audit_partition_rules(
        "tempo",
        _SYN_ENTRIES,
        [(r"^state\.ps\.", _p("lanes", "state"))],
    )
    assert any("no partition rule matches" in f.message for f in findings)


# ----------------------------------------------------------------------
# clean-at-HEAD pins
# ----------------------------------------------------------------------


def test_shard_baseline_is_checked_in_and_reviewed():
    assert os.path.exists(DEFAULT_SHARD_BASELINE)
    base = load_shard_baseline()
    assert sorted(base["ledgers"]) == sorted(ALL_AUDITS)
    for audit, led in base["ledgers"].items():
        assert led, f"empty ledger for {audit}"
        for key, ent in led.items():
            assert ent["verdict"] in (
                SHARDABLE, COLLECTIVE, REPLICATED,
            ), (audit, key)
            reason = str(ent.get("reason", ""))
            assert reason.strip(), (audit, key)
            assert not reason.startswith("UNREVIEWED"), (audit, key)


def test_shard_axis_ledger_summary_is_jax_free():
    import subprocess
    import sys

    # the bench.py metric must stay importable and computable without
    # jax ever loading — proven in a subprocess, not by sys.modules
    # luck in this process
    code = (
        "import sys\n"
        "from fantoch_tpu.lint.shard import shard_axis_ledger_summary\n"
        "s = shard_axis_ledger_summary()\n"
        "assert 'jax' not in sys.modules, 'jax leaked'\n"
        "import json; print(json.dumps(s))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    s = json.loads(out.stdout)
    assert sorted(s["audits"]) == sorted(ALL_AUDITS)
    for counts in s["audits"].values():
        assert counts["axes"] == (
            counts[SHARDABLE] + counts[COLLECTIVE] + counts[REPLICATED]
        )


def test_basic_axis_ledger_clean_at_head():
    """The fast in-tier pin: basic re-proves against the checked-in
    ledger with zero degradations (the full 8-audit pin is the slow
    twin below + the CI shard-gate job)."""
    findings, summary = run_shard(["basic"], include_partial=False)
    assert findings == [], [f.render() for f in findings]
    a = summary["audits"]["basic"]
    assert a["degradations"] == 0 and a["gl502_findings"] == 0


@pytest.mark.slow
def test_all_audits_clean_at_head():
    findings, summary = run_shard()
    assert findings == [], [f.render() for f in findings]
    assert sorted(summary["audits"]) == sorted(ALL_AUDITS)
    base = load_shard_baseline()
    for audit, a in summary["audits"].items():
        assert a["degradations"] == 0, audit
        assert a["stale_baseline"] == [], audit
        assert a["axes"] == len(base["ledgers"][audit]), audit
        if "footprint" in a:
            fp = a["footprint"]
            assert fp["peak_shard_mib"] <= fp["budget_mib"], audit


# ----------------------------------------------------------------------
# baseline cross-pollination guard (report.py write_baseline)
# ----------------------------------------------------------------------


def test_write_baseline_refuses_gl5xx_absorption(tmp_path):
    from fantoch_tpu.lint.report import (
        LintReport, load_baseline, write_baseline,
    )

    report = LintReport()
    report.extend([
        Finding("GL001", "tempo", "a.py:f:add", "keep"),
        Finding("GL501", "tempo", "state.ps.clock:0:N", "drop"),
        Finding("GL502", "tempo", "specs:state.spine:1", "drop"),
        Finding("GL503", "tempo", "core.py:step:group", "drop"),
    ])
    path = str(tmp_path / "baseline.json")
    write_baseline(path, report)
    assert set(load_baseline(path)) == {"GL001:tempo:a.py:f:add"}


# ----------------------------------------------------------------------
# run_sweep wiring: StateShardingError + proof caching + bit-identity
# ----------------------------------------------------------------------


COMMANDS = 2


def _sweep_specs(name, n, lanes=4, conflicts=(0, 100)):
    from fantoch_tpu.core import Config, Planet
    from fantoch_tpu.engine import EngineDims
    from fantoch_tpu.engine.protocols import (
        dev_config_kwargs,
        dev_protocol,
    )
    from fantoch_tpu.parallel.sweep import make_sweep_specs

    planet = Planet.new()
    regions = planet.regions()
    clients = n  # clients_per_region=1 over n-region sets
    dev = dev_protocol(name, clients)
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    specs = make_sweep_specs(
        dev,
        planet,
        region_sets=[
            regions[i : i + n] for i in range(lanes // len(conflicts))
        ],
        fs=[1],
        conflicts=list(conflicts),
        commands_per_client=COMMANDS,
        clients_per_region=1,
        dims=dims,
        config_base=Config(**dev_config_kwargs(name, n, 1)),
    )
    return dev, dims, specs


def _assert_results_equal(xs, ys):
    assert len(xs) == len(ys)
    for a, b in zip(xs, ys):
        assert a.err == b.err
        assert a.completed == b.completed
        assert a.steps == b.steps
        np.testing.assert_array_equal(np.asarray(a.hist), np.asarray(b.hist))
        for key in a.protocol_metrics:
            np.testing.assert_array_equal(
                np.asarray(a.protocol_metrics[key]),
                np.asarray(b.protocol_metrics[key]),
            )


def test_state_shards_requires_mesh_shard_and_divisible_fleet():
    from fantoch_tpu.parallel import partition, run_sweep

    dev, dims, specs = _sweep_specs("basic", 3, lanes=2)
    with pytest.raises(ValueError, match="mesh_shard=True"):
        run_sweep(dev, dims, specs, state_shards=2)
    with pytest.raises(ValueError, match="must be >= 1"):
        run_sweep(dev, dims, specs, mesh_shard=True, state_shards=0)
    with pytest.raises(ValueError, match="does not divide"):
        partition.fleet_mesh_2d(3)  # 8 CPU devices


def test_unproven_layout_raises_state_sharding_error(monkeypatch):
    from fantoch_tpu.parallel import StateShardingError, run_sweep
    from fantoch_tpu.parallel import sweep as sweep_mod

    monkeypatch.setattr(
        "fantoch_tpu.lint.shard.prove_step_state_shardable",
        lambda *a, **k: [
            Finding("GL502", "syn", "specs:state.spine:1",
                    "shards a REPLICATED axis")
        ],
    )
    sweep_mod._STATE_PROOFS.clear()
    dev, dims, specs = _sweep_specs("basic", 3, lanes=2)
    try:
        with pytest.raises(StateShardingError, match="GL502"):
            run_sweep(dev, dims, specs, mesh_shard=True, state_shards=2)
    finally:
        sweep_mod._STATE_PROOFS.clear()


def test_state_proof_is_cached_per_layout(monkeypatch):
    from fantoch_tpu.engine.faults import NO_FAULTS
    from fantoch_tpu.parallel import sweep as sweep_mod
    from fantoch_tpu.parallel.specs import rules_for

    calls = []
    monkeypatch.setattr(
        "fantoch_tpu.lint.shard.prove_step_state_shardable",
        lambda *a, **k: calls.append(1) or [],
    )
    sweep_mod._STATE_PROOFS.clear()
    try:
        from fantoch_tpu.engine.core import init_lane_state

        dev, dims, specs = _sweep_specs("basic", 3, lanes=2)
        state = init_lane_state(dev, dims, specs[0].ctx)
        rules = rules_for("basic")
        args = (dev, dims, False, NO_FAULTS, 0, state, specs[0].ctx,
                rules)
        assert sweep_mod._prove_state_shardable(*args) == ()
        assert sweep_mod._prove_state_shardable(*args) == ()
        assert len(calls) == 1, "proof must be consulted, not re-run"
        # a different declared layout is a different proof
        sweep_mod._prove_state_shardable(
            *args[:-1], [(r"", _p("lanes"))]
        )
        assert len(calls) == 2
    finally:
        sweep_mod._STATE_PROOFS.clear()


def test_state_sharded_sweep_bit_identical_basic():
    """End-to-end 2-D layout on the 8-device mesh: the proof admits
    basic's declared rules, the (4, 2) mesh compiles, and results are
    bit-identical to the single-device reference (n=3 planes fall
    back to replicated placement on the 2-way state axis — the
    divisibility downgrade must never change results)."""
    from fantoch_tpu.parallel import run_sweep

    dev, dims, specs = _sweep_specs("basic", 3, lanes=4)
    sharded = run_sweep(dev, dims, specs, mesh_shard=True, state_shards=2)
    reference = run_sweep(dev, dims, specs, shard_lanes=False)
    _assert_results_equal(sharded, reference)


@pytest.mark.slow
def test_state_sharded_sweep_bit_identical_tempo():
    """The acceptance pin at protocol scale: tempo with n=4 (divisible
    by the 2-way state axis, so ``state.ps.*`` planes REALLY shard
    their N axis) is bit-identical across the 2-D layout."""
    from fantoch_tpu.parallel import run_sweep

    dev, dims, specs = _sweep_specs("tempo", 4, lanes=4)
    sharded = run_sweep(dev, dims, specs, mesh_shard=True, state_shards=2)
    reference = run_sweep(dev, dims, specs, shard_lanes=False)
    _assert_results_equal(sharded, reference)


def test_tempo_n_axis_shard_map_bit_identical():
    """A GL502-proven PartitionSpec for tempo's N axis drives a
    ``shard_map`` run bit-identical to the single-device reference on
    the 8-device CPU mesh — the item-3 pattern in miniature: shard
    the per-process planes over the ``state`` mesh axis, do the
    per-process work shard-locally, and cross processes only through
    one explicit collective at the declared choke."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from fantoch_tpu.parallel import partition, specs

    # the declared + proven layout for tempo's per-process planes
    rules = specs.rules_for("tempo")
    spec = specs.spec_for("state.ps.clocks", rules)
    assert tuple(spec) == (specs.LANES_AXIS, specs.STATE_AXIS)
    led = load_shard_baseline()["ledgers"]["tempo"]
    ents = [v for k, v in led.items()
            if k.startswith("state.ps.clocks:0:")]
    assert ents and ents[0]["verdict"] in (SHARDABLE, COLLECTIVE)
    assert audit_partition_rules("tempo", led, rules) == []

    mesh = partition.fleet_mesh_2d(2)  # (4, 2): lanes x state
    lanes, n, width = 4, 4, 6
    x = np.arange(lanes * n * width, dtype=np.int64)
    x = x.reshape(lanes, n, width) % 97

    def reference(x):
        bumped = x * 3 + 1  # per-process clock work (elementwise)
        # the frontier choke: a cross-process min every shard needs
        lo = jnp.min(bumped, axis=-2, keepdims=True)
        return bumped - lo

    def sharded_body(x):
        bumped = x * 3 + 1
        local = jnp.min(bumped, axis=-2, keepdims=True)
        lo = jax.lax.pmin(local, specs.STATE_AXIS)
        return bumped - lo

    run = jax.jit(
        partition.shard_map(
            sharded_body,
            mesh=mesh,
            in_specs=(P(specs.LANES_AXIS, specs.STATE_AXIS),),
            out_specs=P(specs.LANES_AXIS, specs.STATE_AXIS),
        )
    )
    np.testing.assert_array_equal(
        np.asarray(run(x)), np.asarray(jax.jit(reference)(x))
    )


# ----------------------------------------------------------------------
# selfchecks + CLI (slow: each traces tempo at the audit shape)
# ----------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("kind,rule", [
    ("axis", "GL501"),
    ("spec", "GL502"),
    ("vmem", "GL503"),
])
def test_selfcheck_fixture_names_its_rule(kind, rule):
    findings, summary = run_shard_selfcheck(kind)
    assert findings, f"selfcheck {kind} is vacuously green"
    assert all(f.rule == rule for f in findings)
    assert summary["selfcheck_rule"] == rule


@pytest.mark.slow
@pytest.mark.parametrize("kind,rule", [
    ("axis", "GL501"),
    ("spec", "GL502"),
    ("vmem", "GL503"),
])
def test_cli_selfcheck_exits_nonzero_naming_rule(kind, rule, capsys):
    from fantoch_tpu import cli

    with pytest.raises(SystemExit) as e:
        cli.main(["lint", "--shard-selfcheck", kind])
    assert e.value.code == 1
    captured = capsys.readouterr()
    assert rule in captured.err
    out = json.loads(captured.out.strip().splitlines()[-1])
    assert out["selfcheck"] == kind and out["regressions"] > 0


# ----------------------------------------------------------------------
# registry / naming pins
# ----------------------------------------------------------------------


def test_traced_scan_covers_shard_py_and_specs_py():
    from fantoch_tpu.lint.rules import REPO_ROOT, expand_paths
    from fantoch_tpu.registry import TRACED_SCAN_PATHS

    rels = [
        os.path.relpath(f, REPO_ROOT)
        for f in expand_paths(TRACED_SCAN_PATHS)
    ]
    assert "fantoch_tpu/lint/shard.py" in rels
    assert "fantoch_tpu/parallel/specs.py" in rels


def test_protocol_name_pins_the_naming_convention():
    from fantoch_tpu.engine.protocols import (
        dev_protocol,
        partial_dev_protocol,
    )
    from fantoch_tpu.parallel.specs import RULES, protocol_name

    for name in DEV_PROTOCOLS:
        dev = dev_protocol(name, 3)
        assert protocol_name(dev) == name
        assert name in RULES  # every protocol has a declared layout
    for name in PARTIAL_DEV_PROTOCOLS:
        dev = partial_dev_protocol(name, 4, 2)
        assert protocol_name(dev) == name

"""Fault-injection & recovery harness tests (engine/faults.py).

Three layers of coverage:

1. **Differential**: with identical fault plans, the device engine and
   the host oracle produce bit-identical latency/commit outcomes on
   tie-free faulty schedules — crash-stop plans and link-degradation
   windows for Tempo, FPaxos and Atlas (graph family), plus
   deterministic message drops (threefry verdicts shared by both
   sides) on Basic.
2. **Crash-fault liveness**: lanes with tolerable crash plans terminate
   cleanly (err == 0) with every surviving client's budget executed;
   plans the protocol cannot tolerate terminate immediately with
   ERR_UNAVAIL — no lane hangs, truncates, or reports ERR_STUCK.
3. **Mixed sweeps**: fault-free, crash and partition lanes share one
   compiled sweep with per-lane fault metadata in the results.
"""

import pytest

from fantoch_tpu.client import ConflictPool, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import (
    EngineDims,
    FaultPlan,
    LinkWindow,
    make_lane,
    parse_fault_specs,
    run_lanes,
)
from fantoch_tpu.engine.dims import ERR_STUCK, ERR_TRUNCATED, ERR_UNAVAIL, INF
from fantoch_tpu.engine.faults import unavailable
from fantoch_tpu.engine.protocols import (
    AtlasDev,
    BasicDev,
    EPaxosDev,
    FPaxosDev,
    TempoDev,
    dev_config_kwargs,
    dev_protocol,
)
from fantoch_tpu.protocol import Atlas, Basic, FPaxos, Tempo
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

COMMANDS = 15
CPR = 1

ORACLES = {
    "tempo": Tempo,
    "atlas": Atlas,
    "fpaxos": FPaxos,
    "basic": Basic,
}


def _config(name, n, f):
    return Config(**dev_config_kwargs(name, n, f))


def _dev(name, clients):
    if name == "basic":
        return BasicDev
    if name == "fpaxos":
        return FPaxosDev
    return dev_protocol(name, clients)


def run_oracle(name, config, regions, plan, conflict=100,
               commands=COMMANDS, cpr=CPR, extra=1000):
    planet = Planet.new()
    workload = Workload(
        shard_count=1,
        key_gen=ConflictPool(conflict_rate=conflict, pool_size=1),
        keys_per_command=1,
        commands_per_client=commands,
        payload_size=0,
    )
    runner = Runner(
        ORACLES[name], planet, config, workload, cpr, regions,
        list(regions), fault_plan=plan,
    )
    metrics, _, latencies = runner.run(extra_sim_time_ms=extra)
    fast = slow = stable = 0
    for pm, _em in metrics.values():
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
        stable += pm.get_aggregated(ProtocolMetricsKind.STABLE) or 0
    return latencies, fast, slow, stable


def run_engine(name, config, regions, plan, conflict=100,
               commands=COMMANDS, cpr=CPR):
    planet = Planet.new()
    clients = cpr * len(regions)
    dev = _dev(name, clients)
    total = commands * clients
    dims = EngineDims.for_protocol(
        dev,
        n=config.n,
        clients=clients,
        payload=dev.payload_width(config.n),
        total_commands=total,
        dot_slots=total + 1,
        regions=len(regions),
    )
    spec = make_lane(
        dev,
        planet,
        config,
        conflict_rate=conflict,
        pool_size=1,
        commands_per_client=commands,
        clients_per_region=cpr,
        process_regions=regions,
        client_regions=regions,
        dims=dims,
        faults=plan,
    )
    return run_lanes(dev, dims, [spec])[0]


def assert_latencies_equal(res, oracle_lat, regions):
    """Every region either has no surviving clients on both sides or a
    bit-identical latency distribution. The oracle's per-region tuple
    carries ISSUED commands; completions are the histogram count (they
    differ when a lossy lane leaves commands in flight)."""
    for region in regions:
        dev_done = res.issued(region)
        if region not in oracle_lat:
            assert dev_done == 0, region
            continue
        _issued, hist = oracle_lat[region]
        assert dev_done == hist.count(), region
        if hist.count():
            assert res.latency_mean(region) == hist.mean(), region
            assert res.histogram(region).mean() == hist.mean(), region


# ----------------------------------------------------------------------
# plan construction / validation
# ----------------------------------------------------------------------


def test_fault_plan_validation():
    with pytest.raises(AssertionError):
        LinkWindow(src=0, dst=0, t0=0, t1=10)  # self link
    with pytest.raises(AssertionError):
        LinkWindow(src=0, dst=1, t0=10, t1=10)  # empty window
    with pytest.raises(AssertionError):
        LinkWindow(src=0, dst=1, t0=0, t1=10, mult=0)  # speed-up
    with pytest.raises(AssertionError):
        LinkWindow(src=0, dst=1, t0=0, t1=10, delay=0)  # zero-delay tie
    with pytest.raises(AssertionError):  # overlapping windows, one pair
        FaultPlan(windows=(
            LinkWindow(src=0, dst=1, t0=0, t1=100),
            LinkWindow(src=0, dst=1, t0=50, t1=150),
        ))
    with pytest.raises(AssertionError):  # drops need a horizon
        FaultPlan(drop_bp=100)
    with pytest.raises(AssertionError):  # partitions need a horizon too
        FaultPlan(windows=(
            LinkWindow(src=0, dst=1, t0=0, t1=10, delay=INF),
        ))
    # adjacent windows + reverse direction are fine
    FaultPlan(windows=(
        LinkWindow(src=0, dst=1, t0=0, t1=100),
        LinkWindow(src=0, dst=1, t0=100, t1=200),
        LinkWindow(src=1, dst=0, t0=50, t1=150),
    ))


def test_parse_fault_specs():
    plans = parse_fault_specs(
        '[{}, {"crash": {"1": 200}}, '
        '{"windows": [{"src": 0, "dst": 1, "t0": 0, "t1": 500, '
        '"delay": "inf"}], "horizon": 5000}, '
        '{"drop_bp": 50, "horizon": 3000}]'
    )
    assert plans[0] is None
    assert plans[1].crashes == {1: 200}
    assert plans[2].windows[0].delay >= INF
    assert plans[3].drop_bp == 50 and plans[3].horizon_ms == 3000
    # metadata round-trips through meta() for the results table
    meta = plans[2].meta()
    assert meta["windows"][0]["delay"] == "inf"


def test_min_live_and_unavailable():
    cfg = _config("tempo", 5, 2)
    dev = TempoDev(keys=4)
    # 1 crash: survivors 4 >= fast quorum 4 — tolerable
    assert not unavailable(FaultPlan(crashes={4: 100}), dev, cfg)
    # 2 crashes = f, but survivors 3 < fast quorum 4 — unavailable
    assert unavailable(FaultPlan(crashes={3: 0, 4: 0}), dev, cfg)
    # caesar at n=3 needs all 3 for the fast quorum
    from fantoch_tpu.engine.protocols import CaesarDev

    assert unavailable(
        FaultPlan(crashes={2: 0}), CaesarDev(keys=4),
        _config("caesar", 3, 1),
    )
    # fpaxos tolerates a non-leader crash at n=3, f=1
    assert not unavailable(
        FaultPlan(crashes={2: 0}), FPaxosDev, _config("fpaxos", 3, 1)
    )


# ----------------------------------------------------------------------
# differential: device == oracle on tie-free faulty schedules
# ----------------------------------------------------------------------


@pytest.mark.parametrize("crash_row", [0, 2])
def test_crash_diff_exact_tempo(crash_row):
    n, f = 3, 1
    regions = Planet.new().regions()[:n]
    config = _config("tempo", n, f)
    plan = FaultPlan(crashes={crash_row: 150})
    lat, fast, slow, stable = run_oracle("tempo", config, regions, plan)
    res = run_engine("tempo", config, regions, plan)
    assert not res.err, res.err_cause
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    assert int(res.protocol_metrics["stable"].sum()) == stable
    # surviving clients: everyone not attached to the crashed row
    surviving = (n - 1) * CPR
    assert res.completed == surviving * COMMANDS
    assert_latencies_equal(res, lat, regions)


def test_crash_diff_exact_fpaxos():
    """Crash a write-quorum acceptor: the quorum re-forms from the
    survivors (doomed-last selection) and every surviving client's
    budget completes — identical on device and oracle."""
    n, f = 3, 1
    regions = Planet.new().regions()[:n]
    config = _config("fpaxos", n, f)  # leader = 1 (row 0)
    plan = FaultPlan(crashes={1: 200})
    lat, _fast, _slow, stable = run_oracle("fpaxos", config, regions, plan)
    res = run_engine("fpaxos", config, regions, plan)
    assert not res.err, res.err_cause
    assert int(res.protocol_metrics["stable"].sum()) == stable
    assert res.completed == (n - 1) * CPR * COMMANDS
    assert_latencies_equal(res, lat, regions)


def test_crash_diff_exact_atlas():
    n, f = 3, 1
    regions = Planet.new().regions()[:n]
    config = _config("atlas", n, f)
    plan = FaultPlan(crashes={1: 100})
    lat, fast, slow, stable = run_oracle("atlas", config, regions, plan)
    res = run_engine("atlas", config, regions, plan)
    assert not res.err, res.err_cause
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    assert int(res.protocol_metrics["stable"].sum()) == stable
    assert res.completed == (n - 1) * CPR * COMMANDS
    assert_latencies_equal(res, lat, regions)


def test_window_diff_exact_tempo():
    """Link degradation (no loss): a 6x slowdown window on one link,
    bit-identical on both sides — and strictly worse than fault-free
    for the region behind the degraded link."""
    n, f = 3, 1
    regions = Planet.new().regions()[:n]
    config = _config("tempo", n, f)
    plan = FaultPlan(windows=(
        LinkWindow(src=0, dst=1, t0=50, t1=400, mult=6),
        LinkWindow(src=1, dst=0, t0=50, t1=400, mult=6),
    ))
    lat, fast, slow, stable = run_oracle("tempo", config, regions, plan)
    res = run_engine("tempo", config, regions, plan)
    assert not res.err, res.err_cause
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    assert int(res.protocol_metrics["stable"].sum()) == stable
    assert_latencies_equal(res, lat, regions)

    clean_lat, *_ = run_oracle("tempo", config, regions, None)
    clean_mean = clean_lat[regions[0]][1].mean()
    assert res.latency_mean(regions[0]) > clean_mean


def test_window_overflow_mult_partitions_like_oracle():
    """A multiplier whose product with the base delay crosses INF must
    clamp to INF (= partition) on the device exactly like the oracle's
    min(base*mult, INF) — not wrap negative in i32 and deliver in the
    past (base*mult here is ~5e9, past i32 range)."""
    n, f = 3, 1
    regions = Planet.new().regions()[:n]
    config = _config("tempo", n, f)
    plan = FaultPlan(
        windows=(
            LinkWindow(src=0, dst=1, t0=0, t1=800, mult=1 << 29),
        ),
        horizon_ms=5000,
    )
    lat, *_ = run_oracle("tempo", config, regions, plan)
    res = run_engine("tempo", config, regions, plan)
    assert not res.err, res.err_cause
    assert res.dropped > 0  # the overflowing window actually cut links
    assert res.completed == sum(h.count() for _i, h in lat.values())
    assert_latencies_equal(res, lat, regions)


def test_drop_diff_exact_basic():
    """Probabilistic drops: the threefry verdicts are a pure function
    of (src, dst, channel index), so device and oracle lose the SAME
    messages and complete the SAME commands by the horizon."""
    n, f = 3, 1
    regions = Planet.new().regions()[:n]
    config = _config("basic", n, f)
    plan = FaultPlan(drop_bp=400, drop_seed=7, horizon_ms=4000)
    lat, *_ = run_oracle("basic", config, regions, plan)
    res = run_engine("basic", config, regions, plan)
    # the lane must end at the horizon, not by deadlock detection
    assert not res.err & (ERR_STUCK | ERR_TRUNCATED), res.err_cause
    assert not res.err, res.err_cause
    assert res.dropped > 0, "a 4% drop rate lost no messages?"
    total_oracle = sum(h.count() for _issued, h in lat.values())
    assert 0 < res.completed < 3 * COMMANDS  # loss actually stalled work
    assert res.completed == total_oracle
    assert_latencies_equal(res, lat, regions)


# ----------------------------------------------------------------------
# crash-fault liveness (device-only)
# ----------------------------------------------------------------------


LIVENESS_SHAPES = [
    # (protocol, n, f, conflict, commands, crash rows)
    ("tempo", 3, 1, 100, COMMANDS, {2: 200}),
    ("atlas", 3, 1, 100, COMMANDS, {1: 150}),
    ("epaxos", 3, 1, 100, COMMANDS, {2: 250}),
    ("fpaxos", 3, 1, 100, COMMANDS, {2: 200}),
    ("basic", 3, 1, 100, COMMANDS, {0: 200}),
    # crash at t=0: the doomed process never participates at all
    ("tempo", 3, 1, 100, COMMANDS, {1: 0}),
    pytest.param(
        "caesar", 5, 1, 0, 10, {4: 200}, marks=pytest.mark.slow
    ),
    pytest.param(
        "tempo", 5, 2, 100, 20, {4: 300}, marks=pytest.mark.slow
    ),
]


@pytest.mark.parametrize(
    "name,n,f,conflict,commands,crashes", LIVENESS_SHAPES
)
def test_crash_liveness(name, n, f, conflict, commands, crashes):
    """Tolerable crash plans terminate cleanly with every surviving
    client's budget executed — no hang, no ERR_STUCK, no truncation."""
    regions = Planet.new().regions()[:n]
    config = _config(name, n, f)
    plan = FaultPlan(crashes=crashes)
    res = run_engine(
        name, config, regions, plan, conflict=conflict, commands=commands
    )
    assert res.err == 0, res.err_cause
    survivors = (n - len(crashes)) * CPR
    assert res.completed == survivors * commands
    assert res.faults["crash"] == {
        str(k): v for k, v in crashes.items()
    }


def test_fpaxos_leader_crash_halts_all_clients():
    """No election is modeled: a doomed leader halts every client, and
    the lane still terminates cleanly instead of hanging."""
    n, f = 3, 1
    regions = Planet.new().regions()[:n]
    config = _config("fpaxos", n, f)  # leader = 1 (row 0)
    plan = FaultPlan(crashes={0: 100})
    res = run_engine("fpaxos", config, regions, plan)
    assert res.err == 0, res.err_cause
    assert res.completed == 0
    assert res.faults["halted_clients"] == n * CPR


def test_unavailable_lane_terminates_with_err_unavail():
    """More crashes than the protocol tolerates: the lane flags
    ERR_UNAVAIL immediately — it must not hang toward ERR_STUCK or
    ERR_TRUNCATED."""
    n, f = 3, 1
    regions = Planet.new().regions()[:n]
    config = _config("tempo", n, f)
    plan = FaultPlan(crashes={1: 100, 2: 400})
    res = run_engine("tempo", config, regions, plan)
    assert res.err & ERR_UNAVAIL, res.err_cause
    assert not res.err & (ERR_STUCK | ERR_TRUNCATED), res.err_cause
    assert res.steps <= 2
    assert res.faults["unavail"] is True
    assert res.err_cause == "quorum-unavailable"


# ----------------------------------------------------------------------
# mixed sweep: fault-free + crash + partition under one compiled runner
# ----------------------------------------------------------------------


def test_mixed_fault_sweep():
    from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep

    n, commands = 3, 10
    planet = Planet.new()
    regions = planet.regions()[:n]
    clients = n * CPR
    dev = dev_protocol("tempo", clients)
    total = commands * clients
    dims = EngineDims.for_protocol(
        dev,
        n=n,
        clients=clients,
        payload=dev.payload_width(n),
        total_commands=total,
        dot_slots=total + 1,
        regions=n,
    )
    plans = [
        None,
        FaultPlan(crashes={2: 150}),
        # partition one direction of a link for a while: messages on it
        # are lost, so some commands may stall — bound with a horizon
        FaultPlan(
            windows=(
                LinkWindow(src=0, dst=1, t0=0, t1=600, delay=INF),
            ),
            horizon_ms=5000,
        ),
    ]
    specs = make_sweep_specs(
        dev,
        planet,
        region_sets=[regions],
        fs=[1],
        conflicts=[100],
        commands_per_client=commands,
        clients_per_region=CPR,
        dims=dims,
        config_base=Config(**dev_config_kwargs("tempo", n, 1)),
        faults=plans,
    )
    assert len(specs) == len(plans)
    results = run_sweep(dev, dims, specs)

    clean, crash, part = results
    assert clean.faults is None
    assert clean.err == 0 and clean.completed == total

    assert crash.faults["crash"] == {"2": 150}
    assert crash.err == 0
    assert crash.completed == (n - 1) * CPR * commands

    assert part.faults["windows"][0]["delay"] == "inf"
    assert not part.err & (ERR_STUCK | ERR_TRUNCATED), part.err_cause
    assert part.dropped > 0  # the partition actually cut messages
    # identical workload, identical tie keys: the partition lane can
    # only lose or delay work relative to the clean lane
    assert part.completed <= clean.completed

"""Direct unit tests for the fixed-shape interval sets
(fantoch_tpu/engine/iset.py) — previously exercised only indirectly
through the engine differential suites: insert/merge/contains edge
cases including full-range and adjacent-range coalescing, overflow
flagging, and the gathered-membership equivalence."""

import numpy as np

from fantoch_tpu.engine.iset import (
    iset_add,
    iset_add_range,
    iset_contains,
    iset_contains_gathered,
    iset_empty,
)

G = 4


def as_set(frontier, gaps):
    """Materialize the set's members (reference semantics)."""
    out = set(range(1, int(frontier) + 1))
    for s, e in np.asarray(gaps):
        if s > 0:
            out.update(range(int(s), int(e) + 1))
    return out


def test_empty():
    f, g = iset_empty(G)
    assert as_set(f, g) == set()
    assert not bool(iset_contains(f, g, np.int32(1)))
    assert not bool(iset_contains(f, g, np.int32(0)))


def test_frontier_extension_direct():
    f, g = iset_empty(G)
    f, g, ovf = iset_add_range(f, g, 1, 3)
    assert not bool(ovf)
    assert int(f) == 3 and as_set(f, g) == {1, 2, 3}


def test_gap_buffer_and_adjacent_coalescing():
    f, g = iset_empty(G)
    f, g, _ = iset_add_range(f, g, 1, 2)       # frontier 2
    f, g, _ = iset_add_range(f, g, 5, 6)       # buffered gap
    assert int(f) == 2 and as_set(f, g) == {1, 2, 5, 6}
    # filling 3..4 must absorb the adjacent 5..6 gap into the frontier
    f, g, _ = iset_add_range(f, g, 3, 4)
    assert int(f) == 6
    assert as_set(f, g) == {1, 2, 3, 4, 5, 6}
    assert np.all(np.asarray(g)[:, 0] == 0), "gap slots must be freed"


def test_full_range_coalescing():
    """One add covering everything at once: frontier jumps in one go."""
    f, g = iset_empty(G)
    f, g, ovf = iset_add_range(f, g, 1, 100)
    assert not bool(ovf) and int(f) == 100
    assert bool(iset_contains(f, g, np.int32(100)))
    assert not bool(iset_contains(f, g, np.int32(101)))


def test_chained_gap_absorption():
    """Multiple buffered gaps that all touch once the hole fills must
    absorb in one add (the statically unrolled absorption pass)."""
    f, g = iset_empty(G)
    for s in (3, 5, 7):  # three disjoint single-event gaps
        f, g, ovf = iset_add(f, g, s)
        assert not bool(ovf)
    assert int(f) == 0
    f, g, _ = iset_add_range(f, g, 1, 2)  # 1..2 + 3 + absorb 5? no: 4 missing
    assert int(f) == 3 and as_set(f, g) == {1, 2, 3, 5, 7}
    f, g, _ = iset_add(f, g, 4)  # now 1..5 then 6 missing
    assert int(f) == 5 and as_set(f, g) == {1, 2, 3, 4, 5, 7}
    f, g, _ = iset_add(f, g, 6)  # absorbs the last gap: 1..7
    assert int(f) == 7
    assert np.all(np.asarray(g)[:, 0] == 0)


def test_overlap_union_semantics():
    f, g = iset_empty(G)
    f, g, _ = iset_add_range(f, g, 1, 5)
    f, g, ovf = iset_add_range(f, g, 3, 8)  # overlaps the frontier
    assert not bool(ovf)
    assert int(f) == 8


def test_enable_false_is_noop():
    f, g = iset_empty(G)
    f, g, ovf = iset_add_range(f, g, 1, 5, enable=False)
    assert not bool(ovf) and int(f) == 0 and as_set(f, g) == set()


def test_empty_range_is_noop():
    f, g = iset_empty(G)
    f, g, ovf = iset_add_range(f, g, 5, 4)  # end < start
    assert not bool(ovf) and as_set(f, g) == set()


def test_overflow_flagged():
    f, g = iset_empty(2)
    f, g, o1 = iset_add(f, g, 3)
    f, g, o2 = iset_add(f, g, 5)
    assert not bool(o1) and not bool(o2)
    f2, g2, o3 = iset_add(f, g, 7)  # third disjoint gap: no slot left
    assert bool(o3), "overflow must be reported, not silently dropped"
    # the set itself is unchanged on overflow
    assert as_set(f2, g2) == as_set(f, g)


def test_contains_zero_never_member():
    f, g = iset_empty(G)
    f, g, _ = iset_add_range(f, g, 1, 4)
    assert not bool(iset_contains(f, g, np.int32(0)))


def test_contains_gap_members():
    f, g = iset_empty(G)
    f, g, _ = iset_add_range(f, g, 4, 6)
    for x, want in [(1, False), (3, False), (4, True), (6, True), (7, False)]:
        assert bool(iset_contains(f, g, np.int32(x))) == want, x


def test_contains_gathered_matches_contains():
    """iset_contains_gathered(front[src], gaps[src], x) equivalence over
    a random per-source population."""
    rng = np.random.default_rng(7)
    S = 3
    fronts = np.zeros((S,), np.int32)
    gapss = np.zeros((S, G, 2), np.int32)
    for s in range(S):
        f, g = iset_empty(G)
        for _ in range(5):
            a = int(rng.integers(1, 20))
            b = a + int(rng.integers(0, 3))
            f, g, _ = iset_add_range(f, g, a, b)
        fronts[s] = int(f)
        gapss[s] = np.asarray(g)
    src = np.asarray(rng.integers(0, S, size=(16,)), np.int32)
    x = np.asarray(rng.integers(0, 25, size=(16,)), np.int32)
    got = np.asarray(iset_contains_gathered(fronts, gapss, src, x))
    for i in range(16):
        want = bool(
            iset_contains(fronts[src[i]], gapss[src[i]], x[i])
        )
        assert bool(got[i]) == want, (i, src[i], x[i])

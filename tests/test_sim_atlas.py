"""Atlas whole-protocol simulation tests.

Mirrors fantoch_ps/src/protocol/mod.rs sim_atlas_* tests: 50%-conflict
workloads must be 100% fast path for (n,f) ∈ {(3,1)} (threshold union ==
union with f=1 always holds for n=3 quorums) and take some slow paths
for (5,2).
"""

from fantoch_tpu.core import Config
from fantoch_tpu.protocol import Atlas

from harness import sim_test


def test_sim_atlas_3_1():
    assert sim_test(Atlas, Config(n=3, f=1)) == 0


def test_sim_atlas_5_2():
    assert sim_test(Atlas, Config(n=5, f=2)) > 0

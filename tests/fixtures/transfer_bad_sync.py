"""Deliberately broken host-orchestration code for the GL301 fixture.

Never imported by the package — ``cli.py lint --transfer-selfcheck
sync`` points the transfer ledger here to prove the CI entrypoint
exits non-zero and names GL301 on the seeded defect: a per-**segment**
``.item()`` poll inside the innermost dispatch loop, the exact
serialize-dispatch-with-execution regression the ledger exists to
refuse (docs/PERF.md: each sync costs ~1 s over the tunneled
runtime)."""

from fantoch_tpu.engine.core import build_segment_runner


def drive(state, ctx, untils, max_steps):
    runner, _ = build_segment_runner(state, ctx, max_steps)
    for until in untils:                # sweep -> window tier
        for _ in range(8):              # window -> segment tier
            state, alive = runner(state, ctx, until)
            # GL301 seeded defect: device scalar resolved per segment
            # (tier "segment" — hotter than anything the baseline
            # allows, so this is a new-id regression by name)
            if state["err"].item():
                break
    return state

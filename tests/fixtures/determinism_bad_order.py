"""Seeded-broken fixture for the GL401 ordered-output selfcheck.

Never imported by the package: `cli.py lint --determinism-selfcheck
order` scans this file and must exit non-zero naming GL401, proving
the unordered-iteration prover can actually fail (a crash or an empty
scan would otherwise read as a clean gate).
"""

import json
import os


def merge_journals(path):
    lines = []
    # BUG: unsorted directory scan enumerated into an ordered output —
    # merge order now depends on the filesystem's directory order
    for name in os.listdir(path):
        with open(os.path.join(path, name)) as fh:
            lines.extend(fh.read().splitlines())
    return lines


def rank_points(results):
    winners = {r["point"] for r in results if r["ok"]}
    # BUG: set iteration order materialized into the ranking
    return list(winners)


def summarize(path, results):
    seen = set(r["unit"] for r in results)
    # fine: membership tests never expose iteration order
    missing = [u for u in sorted_units(path) if u not in seen]
    return json.dumps({"missing": missing}, sort_keys=True)


def sorted_units(path):
    # fine: sorted at the source — clean by construction
    return sorted(os.listdir(path))

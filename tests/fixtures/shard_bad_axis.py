"""Seeded-broken fixture for the GL501 ``--shard-selfcheck axis``
selfcheck. Never imported by the package — loaded by file path from
``fantoch_tpu.lint.shard.run_shard_selfcheck`` so CI can prove the
axis-shardability gate is able to fail.

``build_trace()`` returns a tempo step trace whose step was wrapped
with a deliberate cross-process read OUTSIDE every declared choke
function: each per-process plane is reduce-summed in open code, so
every tracked axis of every ``state.ps.*`` plane mixes in a frame the
choke list does not bless. GL501's taint must flip those verdicts to
REPLICATED, and the ledger gate must flag every flip against the
checked-in baseline — at least one GL501 finding, or the gate is
vacuously green.
"""

import jax
import jax.numpy as jnp

from fantoch_tpu.engine.core import _lane_step
from fantoch_tpu.lint.jaxpr import StepTrace
from fantoch_tpu.lint.shard import shard_trace


def build_trace() -> StepTrace:
    real = shard_trace("tempo")

    def leaky_step(s, c):
        out = _lane_step(
            real.protocol, real.dims, s, c, False, real.faults,
            real.monitor_keys,
        )
        # BUG (seeded): a cross-process fold in open code — this frame
        # (`leaky_step`) is not in CHOKE_FNS, so the reduce over each
        # ps plane is an out-of-choke mix on every tracked axis, not a
        # planned collective. The scalar is returned so the equations
        # stay live through the batched replay.
        leak = jnp.float32(0)
        for leaf in jax.tree_util.tree_leaves(s["ps"]):
            leak = leak + jnp.sum(leaf).astype(jnp.float32)
        return out, leak

    closed = jax.make_jaxpr(leaky_step)(real.state, real.ctx)
    return StepTrace(
        real.name, real.protocol, real.dims, real.state, real.ctx,
        real.faults, real.monitor_keys, closed,
    )

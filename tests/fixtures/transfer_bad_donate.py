"""Deliberately broken donation lifetimes for the GL302 fixture.

Never imported by the package — ``cli.py lint --transfer-selfcheck
donate`` points the donation-lifetime prover (lint/alias.py) here to
prove the CI entrypoint exits non-zero and names GL302 on the seeded
defects: a read of a buffer after it was passed at a donated argnum
(use-after-donate — garbage bytes on a real device), a checkpoint
save handed device-fresh state, and an AOT-deserialized executable
invoked with donation without consulting ``aot_donation_safe``."""

from fantoch_tpu.engine.checkpoint import save_boundary
from fantoch_tpu.engine.core import build_segment_runner
from fantoch_tpu.parallel import aot as aot_mod


def use_after_donate(state, ctx, until, max_steps):
    runner, _ = build_segment_runner(state, ctx, max_steps)
    out, alive = runner(state, ctx, until)
    # GL302 seeded defect: `state` was donated to the runner call
    # above — its buffer is dead, this read is use-after-donate
    return out, state["clock"]


def save_device_state(state, ctx, until, max_steps):
    runner, _ = build_segment_runner(state, ctx, max_steps)
    state, alive = runner(state, ctx, until)
    # GL302 seeded defect: checkpoint save of device-fresh state —
    # under donation the npz would capture consumed buffers; the
    # state must round through host_fetch first
    save_boundary(state, until)
    return state


def aot_donate(spec, sig, state, ctx, untils, win, nspec):
    # GL302 seeded defect: donation enabled on a (possibly
    # deserialized) AOT executable without aot_donation_safe()
    runner = aot_mod.get_runner(
        spec, sig, state=state, ctx=ctx, untils=untils,
        window=win, donate=True, narrow=nspec,
    )
    return runner

"""Seeded-broken fixture for the GL403 canonical-serialization
selfcheck.

Never imported by the package: `cli.py lint --determinism-selfcheck
json` scans this file and must exit non-zero naming GL403, proving
the sort_keys/choke-point audit can actually fail.
"""

import json


def write_summary(path, summary):
    # BUG: json.dump without sort_keys=True — summary bytes now depend
    # on dict insertion history, breaking merge/resume cmp pins
    with open(path, "a") as fh:
        json.dump(summary, fh, indent=2)


def append_result(fh, batch, result):
    # BUG: unsorted json.dumps text reaching a write sink
    line = json.dumps({"batch": batch, "result": result})
    fh.write(line + "\n")


def debug_print(point):
    # fine: unsorted dumps to stdout is operator chatter, not a
    # compared artifact
    print(json.dumps(point))

"""Deliberately broken traced code for the AST-lint fixture tests.

Never imported by the package — `cli.py lint --paths` points the AST
scanner here to prove the CI entrypoint exits non-zero on findings
(GL101 raw outbox, GL103 tracer branch, GL104 host ops)."""

import numpy as np

import jax.numpy as jnp


def handle(ps, msg, me, now, ctx, dims):
    # GL103: Python-level branch on a tracer
    if msg["mtype"] > 0:
        seq = ps["own_seq"] + 1
    else:
        seq = ps["own_seq"]
    # GL104: numpy op against tracer values
    limit = np.maximum(seq, 0)
    # GL104: host sync
    count = ps["acks"].item()
    # GL101: raw outbox dict bypassing emit/emit_broadcast/pack_outbox
    return ps, {
        "valid": jnp.ones((4,), bool),
        "dst": jnp.zeros((4,), jnp.int32),
        "mtype": jnp.full((4,), limit, jnp.int32),
        "payload": jnp.zeros((4, 3), jnp.int32),
        "delay": jnp.full((4,), count, jnp.int32),
        "src": jnp.full((4,), -1, jnp.int32),
    }

"""Seeded GL602 defect: a union storage extent below a native extent.

The skeleton selfcheck (``lint --skeleton-selfcheck branch``) loads the
real checked-in ledger, lets this fixture shrink ONE shared state
plane's union extent below tempo's native extent, and then proves the
tempo branch against the mutated skeleton. unpack_state's post-slice
shape check refuses by name ("the union extent does not cover the
native extent"), so the branch-compatibility prover must fail GL602 —
exactly what a hand-edited ledger that under-declares a plane would do
to the ``lax.switch`` megabatch.
"""


def mutate_planes(entries):
    for name in sorted(entries):
        if not name.startswith("state."):
            continue
        ent = entries[name]
        if ent.get("verdict") != "SHARED":
            continue
        native = ent.get("native", {}).get("tempo")
        if native is None or not native.get("shape"):
            continue
        # shrink the first axis of the union below tempo's native
        # extent: the unpack slice can no longer cover the plane
        shape, _ = list(native["shape"]), native["dtype"]
        if shape[0] < 1:
            continue
        union = dict(ent["union"])
        ushape = list(union["shape"])
        ushape[0] = shape[0] - 1
        union["shape"] = ushape
        entries[name] = dict(ent, union=union)
        return entries
    raise AssertionError(
        "no SHARED state plane with a shrinkable extent found"
    )

"""Seeded GL605 defect: a mixed batch whose lanes were mis-routed.

The skeleton selfcheck (``lint --skeleton-selfcheck mixed``) runs the
REAL tiny basic+tempo mixed batch through the protocol_id-switched
runner, then lets this fixture swap two lanes' canonical result rows —
exactly what a switch that routed a lane to the wrong branch (or a
regroup that inverted the wrong permutation) would produce. The GL605
compare against the homogeneous controls must fail by name, or the
mixed-batch identity gate is vacuously green.
"""


def mutate_rows(rows):
    rows = list(rows)
    # lane 0 is basic, lane 1 is tempo: swapping them is the smallest
    # cross-branch mis-route, guaranteed to diverge from both controls
    rows[0], rows[1] = rows[1], rows[0]
    return rows

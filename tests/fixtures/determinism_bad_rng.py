"""Seeded-broken fixture for the GL402 PRNG-discipline selfcheck.

Never imported by the package: `cli.py lint --determinism-selfcheck
rng` scans this file and must exit non-zero naming GL402, proving the
ambient-nondeterminism audit can actually fail.
"""

import json
import os
import random
import time
import uuid


def journal_entry(path, unit, result):
    # BUG: wall-clock baked into a journal entry — two byte-identical
    # re-runs now journal different bytes
    entry = {"unit": unit, "result": result, "at": time.time()}
    with open(path, "a") as fh:
        fh.write(json.dumps(entry, sort_keys=True) + "\n")


def artifact_name(out_dir):
    # BUG: uuid-derived artifact names — the merged artifact set is
    # never reproducible across runs
    return os.path.join(out_dir, f"repro_{uuid.uuid4()}.json")


def jitter_schedule(n):
    # BUG: default-stream randomness (no journaled seed) feeding a
    # result-affecting schedule
    plan = [random.randint(0, 7) for _ in range(n)]
    return json.dumps({"plan": plan}, sort_keys=True)


def budget_left(deadline, t0):
    # fine: perf_counter timing is budget metadata, stripped from
    # every compared artifact — not a GL402 source
    elapsed = time.perf_counter() - t0
    return deadline - elapsed

"""Seeded-broken fixture for the GL503 ``--shard-selfcheck vmem``
selfcheck. Never imported by the package — loaded by file path from
``fantoch_tpu.lint.shard.run_shard_selfcheck`` so CI can prove the
per-shard footprint gate is able to fail.

``CANDIDATES`` declares a tempo mesh whose per-shard budget cannot
hold even one fused group of the shard-divided step (the measured
peak at the audit shape is ~164 MiB): the footprint check must reject
the layout by name — at least one GL503 finding, or the gate is
vacuously green.
"""

# BUG (seeded): a quarter-MiB budget on a step whose largest
# shard-divided fused group measures ~164 MiB at the audit shape
CANDIDATES = {
    "tempo": {"lanes": 4, "state": 2, "budget_mib": 0.25},
}

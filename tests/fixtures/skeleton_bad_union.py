"""Seeded GL601 defect: one protocol's plane dtype silently widened.

The skeleton selfcheck (``lint --skeleton-selfcheck union``) loads this
fixture, asks it for per-audit plane specs, and reclassifies them
against the real checked-in ledger. The fixture reconstructs the specs
from the ledger itself, then widens ONE audit's copy of a plane that is
SHARED at HEAD to int64 — exactly the drift a protocol edit would
introduce — so the reclassification flips the plane's verdict
(SHARED -> CASTABLE) and the GL601 gate must fail naming the plane.
"""


def plane_specs():
    from fantoch_tpu.lint.skeleton import (
        load_skeleton_baseline,
        specs_from_baseline,
    )

    specs = specs_from_baseline(load_skeleton_baseline())
    audits = sorted(specs)
    assert audits, "checked-in skeleton ledger is empty"
    victim_audit = "tempo" if "tempo" in specs else audits[0]
    # find a plane that is SHARED at HEAD: present in every audit, one
    # rank, every copy int32 — widening one copy makes it CASTABLE
    for name in sorted(specs[victim_audit]):
        copies = [specs[a].get(name) for a in audits]
        if any(c is None for c in copies):
            continue
        ranks = {len(shape) for shape, _ in copies}
        dtypes = {dtype for _, dtype in copies}
        if ranks != {len(copies[0][0])} or dtypes != {"int32"}:
            continue
        shape, _ = specs[victim_audit][name]
        specs[victim_audit][name] = (shape, "int64")
        return specs
    raise AssertionError(
        "no SHARED int32 plane found to seed the dtype drift"
    )

"""Seeded-broken fixture for the GL404 atomic-artifact selfcheck.

Never imported by the package: `cli.py lint --determinism-selfcheck
write` scans this file and must exit non-zero naming GL404, proving
the atomic-write audit can actually fail.
"""

import json
import pathlib


def save_frontier(path, frontier):
    # BUG: raw open-for-write of a durable artifact — a kill mid-write
    # leaves a torn frontier.json the resume path then chokes on
    with open(path, "w") as fh:
        json.dump(frontier, fh, indent=2, sort_keys=True)


def save_key(path, key_bytes):
    # BUG: Path.write_bytes is the same torn-write class
    pathlib.Path(path).write_bytes(key_bytes)


def save_note(path, text):
    # BUG: write_text too
    pathlib.Path(path).write_text(text)


def append_journal(path, line):
    # fine: append mode is the sanctioned journal protocol (torn final
    # lines are tolerated on read)
    with open(path, "a") as fh:
        fh.write(line + "\n")

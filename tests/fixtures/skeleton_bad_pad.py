"""Seeded GL603 defect: an impossible amplification budget.

The skeleton selfcheck (``lint --skeleton-selfcheck pad``) budgets the
real checked-in GL601 ledger against this grid declaration. A
heterogeneous union is never free — every protocol pays at least the
other members' private slots — so a 1.01x budget over the full grid
must trip the padding-amplification gate naming GL603 and the worst
member. If it ever passes, the byte model (or the gate) is broken.
"""

GRIDS = {
    "full-grid": {
        "audits": (
            "basic", "fpaxos", "tempo", "atlas", "epaxos", "caesar",
            "tempo@2shards", "atlas@2shards",
        ),
        "max_amplification": 1.01,
    },
}

"""Seeded-broken fixture for the GL502 ``--shard-selfcheck spec``
selfcheck. Never imported by the package — loaded by file path from
``fantoch_tpu.lint.shard.run_shard_selfcheck`` so CI can prove the
partition-rule auditor is able to fail.

``RULES`` declares a tempo layout that shards the first state axis of
EVERY plane — including the planes GL501's checked-in ledger proves
REPLICATED (min-reduced spines, ``next_periodic``-style scalars) —
plus a dead rule whose regex matches no plane. The auditor must
refuse both by name: at least one GL502 finding, or the gate is
vacuously green.
"""

from jax.sharding import PartitionSpec as P

from fantoch_tpu.parallel.specs import LANES_AXIS, STATE_AXIS

RULES = {
    "tempo": [
        # BUG (seeded): dead rule — no tempo plane is named this, so
        # this layout silently never applies
        (r"^state\.nonexistent_plane\.", P(LANES_AXIS, STATE_AXIS)),
        # BUG (seeded): catch-all that shards plane axis 0 of every
        # plane; GL501 proves many of those axes REPLICATED, and a
        # REPLICATED axis behind a `state` entry would change results
        (r"", P(LANES_AXIS, STATE_AXIS)),
    ],
}

"""Time-varying traffic schedules (fantoch_tpu/traffic, docs/TRAFFIC.md).

Four contracts are pinned here:

1. **Flat is free** — a flat ``TrafficSchedule`` collapses to the
   static ctx path: same ctx fields, byte-identical ``LaneResults``,
   and (GL005-style) an alpha-equivalent traced jaxpr — so the
   seed-warmed XLA cache and the gating pin survive the subsystem.
2. **Exact key mirroring** — the device's epoch-indexed key stream and
   the host ``DeviceStream(traffic=...)`` replay are element-identical
   at a fixed seed, and a hot-key-churn epoch boundary lands on the
   exact command seq (not ±1).
3. **Bit-exact differential** — tempo and fpaxos under fault plans run
   a time-varying schedule bit-exactly between the vmapped engine and
   the host oracle (latency distributions + protocol metrics).
4. **Campaign/bote wiring** — the sweep campaign's ``traffic`` axis
   runs per-preset batch groups, a resume onto a different schedule is
   refused *by name* at both the campaign and checkpoint layers, and
   ``bote/validate.py`` emits a schema-valid frontier artifact.
"""

import json
import os

import numpy as np
import pytest

from fantoch_tpu.client import Workload
from fantoch_tpu.client.key_gen import DeviceStream, KeyGenState
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import (
    EngineDims,
    FaultPlan,
    LinkWindow,
    make_lane,
    run_lanes,
)
from fantoch_tpu.engine.protocols import FPaxosDev, TempoDev
from fantoch_tpu.protocol import FPaxos, Tempo
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.registry import TRAFFIC_PRESETS, traffic_preset
from fantoch_tpu.sim import Runner
from fantoch_tpu.traffic import TrafficPhase, TrafficSchedule, resolve_traffic

COMMANDS = 8
CPR = 1


def _tv_schedule(commands=COMMANDS):
    """A schedule exercising every knob: conflict shift, pool churn,
    think curve, read mix."""
    return TrafficSchedule(
        "tv",
        (
            TrafficPhase(commands=3, conflict_rate=100, pool_size=1,
                         pool_base=0, think_ms=4, read_pct=60),
            TrafficPhase(commands=2, conflict_rate=50, pool_size=2,
                         pool_base=1, think_ms=0, read_pct=20),
            TrafficPhase(commands=3, conflict_rate=100, pool_size=1,
                         pool_base=3, think_ms=1, read_pct=40),
        ),
    )


# ----------------------------------------------------------------------
# schedule spec
# ----------------------------------------------------------------------


def test_schedule_spec():
    s = _tv_schedule()
    assert s.pattern_len == 8
    assert s.pool_span() == 4
    assert not s.is_flat()
    # epoch boundaries on exact seqs (1-based)
    assert [s.epoch_of(q) for q in range(1, 9)] == [0, 0, 0, 1, 1, 2, 2, 2]
    # cycle=False: last phase extends
    assert s.epoch_of(100) == 2
    cyc = TrafficSchedule("c", s.phases, cycle=True)
    assert cyc.epoch_of(9) == 0 and cyc.epoch_of(12) == 1
    # think mirror helper == table content
    tables = s.compile(COMMANDS)
    assert tables["traffic_seq_epoch"].shape == (COMMANDS + 2,)
    for seq in range(1, COMMANDS + 2):
        e = int(tables["traffic_seq_epoch"][seq])
        assert e == s.epoch_of(seq)
        assert int(tables["traffic_think"][e]) == s.think_ms(seq)
    assert int(tables["traffic_pool_span"]) == 4
    # JSON round trip preserves value equality
    assert TrafficSchedule.from_json(s.to_json()) == s
    # flatness: single knob tuple, no think, no rotation (read-mix-only
    # variation is still flat for the device)
    flat = TrafficSchedule(
        "f",
        (
            TrafficPhase(commands=2, conflict_rate=30, read_pct=80),
            TrafficPhase(commands=2, conflict_rate=30, read_pct=10),
        ),
    )
    assert flat.is_flat()
    assert not TrafficSchedule(
        "nf", (TrafficPhase(commands=2, conflict_rate=30, think_ms=1),)
    ).is_flat()
    with pytest.raises(AssertionError):
        TrafficPhase(commands=0, conflict_rate=50)
    with pytest.raises(AssertionError):
        TrafficPhase(commands=1, conflict_rate=101)


def test_presets_resolve():
    for name in TRAFFIC_PRESETS:
        sched = resolve_traffic(
            name, conflict=40, pool_size=2, commands=20
        )
        if name == "flat":
            assert sched is None
            continue
        assert isinstance(sched, TrafficSchedule)
        assert sched.name == name
        if name == "churn":
            # rotation moves the pool each quarter, span covers all
            bases = {p.pool_base for p in sched.phases}
            assert len(bases) == 4
            assert sched.pool_span() == 8
        if name == "flash":
            assert max(p.conflict_rate for p in sched.phases) == 100
        if name == "diurnal":
            assert sched.cycle
            assert {p.conflict_rate for p in sched.phases} == {40}
    with pytest.raises(ValueError):
        traffic_preset("nope", conflict=0, commands=5)


# ----------------------------------------------------------------------
# flat == static (byte-identical results + alpha-equivalent trace)
# ----------------------------------------------------------------------


def _tempo_setup(commands=COMMANDS, keys_extra=0, n=3):
    planet = Planet.new()
    regions = planet.regions()[:n]
    config = Config(n=n, f=1, gc_interval_ms=100,
                    tempo_detached_send_interval_ms=100)
    clients = CPR * n
    dev = TempoDev(keys=1 + keys_extra + clients)
    total = commands * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    return planet, regions, config, dev, dims


def test_flat_schedule_byte_identical():
    planet, regions, config, dev, dims = _tempo_setup()

    def lane(traffic):
        return make_lane(
            dev, planet, config, conflict_rate=100, pool_size=1,
            commands_per_client=COMMANDS, clients_per_region=CPR,
            process_regions=regions, client_regions=regions, dims=dims,
            traffic=traffic,
        )

    static = lane(None)
    flat_preset = lane("flat")
    flat_sched = lane(
        TrafficSchedule(
            "myflat", (TrafficPhase(commands=4, conflict_rate=100),)
        )
    )
    for spec in (flat_preset, flat_sched):
        assert spec.ctx.keys() == static.ctx.keys()
        assert spec.traffic_meta is None
        for k in static.ctx:
            assert np.array_equal(static.ctx[k], spec.ctx[k]), k
    r0, r1, r2 = run_lanes(dev, dims, [static, flat_preset, flat_sched])
    a = json.dumps(r0.to_json(), sort_keys=True)
    assert a == json.dumps(r1.to_json(), sort_keys=True)
    assert a == json.dumps(r2.to_json(), sort_keys=True)


def test_flat_schedule_trace_alpha_equivalent():
    """GL005-style pin: the flat-schedule step traces a graph
    alpha-equivalent to HEAD's static trace, and a non-flat schedule
    traces a genuinely different one (the tables are real)."""
    from fantoch_tpu.engine.core import init_lane_state
    from fantoch_tpu.lint.gating import alpha_equivalent
    from fantoch_tpu.lint.jaxpr import trace_step

    planet, regions, config, dev, dims = _tempo_setup(
        commands=2, keys_extra=4
    )

    def trace(traffic, name):
        spec = make_lane(
            dev, planet, config, conflict_rate=100, pool_size=1,
            commands_per_client=2, clients_per_region=CPR,
            process_regions=regions, client_regions=regions, dims=dims,
            traffic=traffic,
        )
        state = init_lane_state(dev, dims, spec.ctx)
        return trace_step(dev, dims, state, spec.ctx, name=name)

    static = trace(None, "static")
    flat = trace("flat", "flat")
    ok, why = alpha_equivalent(static.closed, flat.closed)
    assert ok, f"flat schedule changed the traced step: {why}"
    churn = trace(
        TrafficSchedule(
            "churn2",
            (
                TrafficPhase(commands=1, conflict_rate=100, pool_base=0),
                TrafficPhase(commands=1, conflict_rate=100, pool_base=2),
            ),
        ),
        "churn",
    )
    ok, _why = alpha_equivalent(static.closed, churn.closed)
    assert not ok, "a churn schedule must change the traced step"


# ----------------------------------------------------------------------
# device keys == host stream keys, boundary-exact churn
# ----------------------------------------------------------------------


def test_device_keys_match_host_stream_churn_boundary():
    import jax

    from fantoch_tpu.engine.core import key_table_fn, keygen_ctx_fields

    planet, regions, config, dev, dims = _tempo_setup(keys_extra=4)
    boundary = 4  # pool rotates AT seq 5 (first seq of phase 2)
    sched = TrafficSchedule(
        "churnx",
        (
            TrafficPhase(commands=boundary, conflict_rate=100,
                         pool_size=2, pool_base=0),
            TrafficPhase(commands=COMMANDS - boundary, conflict_rate=100,
                         pool_size=2, pool_base=2),
        ),
    )
    seed = 3
    spec = make_lane(
        dev, planet, config, conflict_rate=100, pool_size=2,
        commands_per_client=COMMANDS,
        clients_per_region=CPR, process_regions=regions,
        client_regions=regions, dims=dims, seed=seed, traffic=sched,
    )
    import jax.numpy as jnp

    C = dims.C
    keyctx = {
        k: jnp.asarray(spec.ctx[k]) for k in keygen_ctx_fields(spec.ctx)
    }
    table = np.asarray(jax.jit(key_table_fn(C, COMMANDS + 1))(keyctx))

    for client in range(C):
        # host mirror: the oracle's per-client key stream
        state = KeyGenState(
            DeviceStream(conflict_rate=100, pool_size=2, seed=seed,
                         traffic=sched),
            shard_count=1,
            client_id=client + 1,
        )
        host = [state.gen_cmd_key() for _ in range(COMMANDS)]
        device = [str(int(table[client, s])) for s in range(1, COMMANDS + 1)]
        assert host == device, f"client {client}"
        # churn boundary exact: conflict=100 ⇒ every key is a pool key;
        # epoch 0 pool is [0, 2), epoch 1 pool is [2, 4) — the switch
        # happens AT seq boundary+1, not ±1
        for s, key in enumerate(device, start=1):
            lo, hi = (0, 2) if s <= boundary else (2, 4)
            assert lo <= int(key) < hi, (s, key)


def test_device_keys_match_host_stream_epoch_zipf():
    """Epoch-varying Zipf: the device's per-epoch cumulative table
    (ctx["traffic_zipf_cum"]) and the host DeviceStream mirror draw
    element-identical keys, and the skew shift is real — the same lane
    without the schedule draws a different stream."""
    import jax
    import jax.numpy as jnp

    from fantoch_tpu.engine.core import key_table_fn, keygen_ctx_fields

    planet, regions, config, dev, dims = _tempo_setup(keys_extra=4)
    sched = TrafficSchedule(
        "zipfvar",
        (
            TrafficPhase(commands=4, conflict_rate=100, pool_size=1),
            # coef 8.0 pins nearly all mass on rank 1 — visibly skewed
            TrafficPhase(commands=COMMANDS - 4, conflict_rate=100,
                         pool_size=1, zipf_coef=8.0),
        ),
    )
    assert sched.has_zipf_override()
    seed, zipf = 7, (1.0, 6)

    def table_for(traffic):
        spec = make_lane(
            dev, planet, config, conflict_rate=100, pool_size=1,
            commands_per_client=COMMANDS, clients_per_region=CPR,
            process_regions=regions, client_regions=regions, dims=dims,
            seed=seed, zipf=zipf, traffic=traffic,
        )
        keyctx = {
            k: jnp.asarray(spec.ctx[k])
            for k in keygen_ctx_fields(spec.ctx)
        }
        return np.asarray(jax.jit(key_table_fn(dims.C, COMMANDS + 1))(keyctx))

    table = table_for(sched)
    for client in range(dims.C):
        state = KeyGenState(
            DeviceStream(conflict_rate=100, pool_size=1, seed=seed,
                         zipf=zipf, traffic=sched),
            shard_count=1,
            client_id=client + 1,
        )
        host = [state.gen_cmd_key() for _ in range(COMMANDS)]
        device = [str(int(table[client, s])) for s in range(1, COMMANDS + 1)]
        assert host == device, f"client {client}"
    # the override is not a no-op: dropping the schedule (base coef
    # everywhere) changes the drawn stream at the same seed
    assert not np.array_equal(table, table_for(None))


# ----------------------------------------------------------------------
# device vs oracle bit-exact under faults + time-varying schedule
# ----------------------------------------------------------------------


def _run_oracle(protocol_cls, config, regions, sched, plan, seed=0,
                commands=COMMANDS):
    planet = Planet.new()
    workload = Workload(
        shard_count=1,
        key_gen=DeviceStream(conflict_rate=100, pool_size=1, seed=seed,
                             traffic=sched),
        keys_per_command=1,
        commands_per_client=commands,
        payload_size=0,
    )
    runner = Runner(
        protocol_cls, planet, config, workload, CPR, regions,
        list(regions), seed=seed, fault_plan=plan, traffic=sched,
    )
    metrics, _, latencies = runner.run(extra_sim_time_ms=1000)
    fast = slow = stable = 0
    for pm, _em in metrics.values():
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
        stable += pm.get_aggregated(ProtocolMetricsKind.STABLE) or 0
    return latencies, fast, slow, stable


def _assert_latencies_equal(res, oracle_lat, regions):
    for region in regions:
        dev_done = res.issued(region)
        if region not in oracle_lat:
            assert dev_done == 0, region
            continue
        _issued, hist = oracle_lat[region]
        assert dev_done == hist.count(), region
        if hist.count():
            assert res.latency_mean(region) == hist.mean(), region
            assert res.histogram(region).mean() == hist.mean(), region


def test_engine_oracle_bitexact_traffic_faults_tempo():
    """Tempo, crash plan + link-degradation window, time-varying
    schedule (think + churn + conflict shift): engine ≡ oracle."""
    n, seed = 3, 0
    planet = Planet.new()
    regions = planet.regions()[:n]
    config = Config(n=n, f=1, gc_interval_ms=100,
                    tempo_detached_send_interval_ms=100)
    sched = _tv_schedule()
    plan = FaultPlan(
        crashes={2: 260},
        windows=(LinkWindow(src=0, dst=1, t0=40, t1=220, mult=3),),
    )
    clients = CPR * n
    dev = TempoDev(keys=sched.pool_span() + clients)
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    spec = make_lane(
        dev, planet, config, conflict_rate=100, pool_size=1,
        commands_per_client=COMMANDS, clients_per_region=CPR,
        process_regions=regions, client_regions=regions, dims=dims,
        seed=seed, faults=plan, traffic=sched,
    )
    res = run_lanes(dev, dims, [spec])[0]
    assert not res.err, res.err_cause
    oracle_lat, fast, slow, stable = _run_oracle(
        Tempo, config, regions, sched, plan, seed=seed
    )
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    assert int(res.protocol_metrics["stable"].sum()) == stable
    _assert_latencies_equal(res, oracle_lat, regions)


def test_engine_oracle_bitexact_traffic_faults_fpaxos():
    """FPaxos (leader-based), non-leader crash + window, same
    time-varying schedule: engine ≡ oracle."""
    n, seed = 3, 1
    planet = Planet.new()
    regions = planet.regions()[:n]
    config = Config(n=n, f=1, gc_interval_ms=100, leader=1)
    sched = _tv_schedule()
    plan = FaultPlan(
        crashes={2: 300},
        windows=(LinkWindow(src=1, dst=0, t0=0, t1=150, mult=2),),
    )
    clients = CPR * n
    dev = FPaxosDev
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    spec = make_lane(
        dev, planet, config, conflict_rate=100, pool_size=1,
        commands_per_client=COMMANDS, clients_per_region=CPR,
        process_regions=regions, client_regions=regions, dims=dims,
        seed=seed, faults=plan, traffic=sched,
    )
    res = run_lanes(dev, dims, [spec])[0]
    assert not res.err, res.err_cause
    oracle_lat, fast, slow, stable = _run_oracle(
        FPaxos, config, regions, sched, plan, seed=seed
    )
    assert int(res.protocol_metrics["stable"].sum()) == stable
    _assert_latencies_equal(res, oracle_lat, regions)


def test_traffic_lane_mixing_refused():
    """Lanes with and without epoch tables trace different graphs and
    must never share a batch."""
    planet, regions, config, dev, dims = _tempo_setup(keys_extra=4)

    def lane(traffic):
        return make_lane(
            dev, planet, config, conflict_rate=100, pool_size=1,
            commands_per_client=COMMANDS, clients_per_region=CPR,
            process_regions=regions, client_regions=regions, dims=dims,
            traffic=traffic,
        )

    with pytest.raises(AssertionError, match="traffic tables"):
        run_lanes(dev, dims, [lane(None), lane(_tv_schedule())])


# ----------------------------------------------------------------------
# campaign traffic axis + refusal by name
# ----------------------------------------------------------------------


def test_campaign_traffic_axis_and_refusals(tmp_path):
    from fantoch_tpu.campaign import (
        CampaignError,
        campaign_from_json,
        run_campaign,
    )

    grid = {
        "kind": "sweep",
        "protocols": ["basic"],
        "ns": [3],
        "conflicts": [100],
        "subsets": 1,
        "commands_per_client": 2,
        "batch_lanes": 2,
        "segment_steps": 64,
        "traffic": ["flat", "churn"],
    }
    spec = campaign_from_json(grid)
    path = str(tmp_path / "c1")
    summary = run_campaign(path, spec)
    assert summary["done"], summary
    assert summary["errors"] == 0
    # per-preset batch groups journaled under traffic-tagged ids
    ids = set()
    with open(os.path.join(path, "journal.jsonl")) as fh:
        for line in fh:
            ids.add(json.loads(line)["id"])
    assert any("/tchurn/" in i for i in ids), ids
    assert any("/tchurn/" not in i for i in ids), ids
    assert os.path.exists(os.path.join(path, "results.jsonl"))

    # resume onto a different traffic grid: refused by the stored-spec
    # equality check, by name
    other = campaign_from_json({**grid, "traffic": ["diurnal"]})
    with pytest.raises(CampaignError):
        run_campaign(path, other)

    # unknown preset refused at parse time
    with pytest.raises(CampaignError, match="traffic preset"):
        campaign_from_json({**grid, "traffic": ["rush_hour"]})


def test_checkpoint_refuses_schedule_swap(tmp_path):
    """The sweep checkpoint names its schedule: resuming churn lanes
    onto a diurnal checkpoint raises a CheckpointMismatchError naming
    `traffic` (the ctx bit-compare would also catch a silent value
    swap — this pins the by-name layer)."""
    from fantoch_tpu.engine.checkpoint import (
        CheckpointMismatchError,
        CheckpointSpec,
        SweepInterrupted,
    )
    from fantoch_tpu.engine.protocols import BasicDev
    from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep

    planet = Planet.new()
    regions = planet.regions()[:3]
    commands = 2
    clients = 3
    total = commands * clients
    dev = BasicDev
    dims = EngineDims.for_protocol(
        dev, n=3, clients=clients, payload=dev.payload_width(3),
        total_commands=total, dot_slots=total + 1, regions=3,
    )

    def specs(traffic):
        return make_sweep_specs(
            dev, planet, region_sets=[regions], fs=[1], conflicts=[100],
            commands_per_client=commands, clients_per_region=1,
            dims=dims, traffic=traffic,
        )

    ck = str(tmp_path / "ck")
    # scan_window=1: the interrupt must land mid-batch (the default
    # window would cover the whole tiny batch in one device call)
    with pytest.raises(SweepInterrupted):
        run_sweep(
            dev, dims, specs("diurnal"), segment_steps=8, scan_window=1,
            checkpoint=CheckpointSpec(
                path=ck, keep=True, stop_after_segments=1
            ),
        )
    with pytest.raises(CheckpointMismatchError, match="traffic"):
        run_sweep(
            dev, dims, specs("churn"), segment_steps=8,
            checkpoint=CheckpointSpec(path=ck, keep=True),
        )
    # the matching schedule resumes fine and completes
    results = run_sweep(
        dev, dims, specs("diurnal"), segment_steps=8,
        checkpoint=CheckpointSpec(path=ck),
    )
    assert len(results) == 1 and not results[0].err

    # legacy compatibility: a pre-traffic checkpoint (no `traffic` meta
    # key at all) must still resume a flat/static run — the by-name
    # check only applies to scheduled batches (the signature and ctx
    # compares cover everything else)
    ck2 = str(tmp_path / "ck_legacy")
    with pytest.raises(SweepInterrupted):
        run_sweep(
            dev, dims, specs(None), segment_steps=8, scan_window=1,
            checkpoint=CheckpointSpec(
                path=ck2, keep=True, stop_after_segments=1
            ),
        )
    mpath = os.path.join(ck2, "manifest.json")
    manifest = json.load(open(mpath))
    assert manifest["meta"].pop("traffic") == ["flat"]
    with open(mpath, "w") as fh:
        json.dump(manifest, fh)
    results = run_sweep(
        dev, dims, specs(None), segment_steps=8,
        checkpoint=CheckpointSpec(path=ck2),
    )
    assert len(results) == 1 and not results[0].err


# ----------------------------------------------------------------------
# bote frontier validation
# ----------------------------------------------------------------------


def test_bote_validate_dryrun(tmp_path):
    from fantoch_tpu.bote.validate import (
        check_frontier_artifact,
        frontier_candidates,
        validate_frontier,
    )

    planet = Planet.new()
    cands = frontier_candidates(planet, 3, 2)
    assert len(cands) == 2
    assert all(len(c.regions) == 3 for c in cands)
    # closed-form stats carry the model keys + percentiles
    for c in cands:
        assert "ff1" in c.closed_form and "e" in c.closed_form
        assert c.closed_form["af1"]["p99"] >= c.closed_form["af1"]["p50"]
    artifact, summary = validate_frontier(
        str(tmp_path / "bote"), planet=planet, candidates=cands,
        traffic=("flat", "diurnal"), dryrun=True,
    )
    assert summary["done"] and summary["dryrun"]
    check_frontier_artifact(artifact)
    on_disk = json.load(open(summary["artifact"]))
    check_frontier_artifact(on_disk)
    assert on_disk["traffic"] == ["flat", "diurnal"]
    # a broken artifact fails the schema check
    bad = json.loads(json.dumps(artifact))
    del bad["candidates"][0]["closed_form"]["af1"]["p99"]
    with pytest.raises(AssertionError):
        check_frontier_artifact(bad)

    # errored measured points must carry nulls + a cause — numeric
    # percentiles from a failed lane are refused by the gate
    def measured_artifact(stats):
        art = json.loads(json.dumps(artifact))
        art["dryrun"] = False
        for c in art["candidates"]:
            c["measured"] = {
                p: {
                    "f1": {
                        t: {str(cf): dict(stats) for cf in art["conflicts"]}
                        for t in art["traffic"]
                    }
                }
                for p in art["protocols"]
            }
        return art

    ok_err = {"mean": None, "p50": None, "p99": None, "count": 0,
              "lanes": 1, "errors": 1, "error_cause": "pool-overflow"}
    check_frontier_artifact(measured_artifact(ok_err))
    fake = {"mean": 0.0, "p50": 0.0, "p99": 0.0, "count": 0,
            "lanes": 1, "errors": 1}
    with pytest.raises(AssertionError):
        check_frontier_artifact(measured_artifact(fake))


@pytest.mark.slow
def test_bote_validate_measured(tmp_path):
    """The full measured loop at a tiny shape: campaign per candidate,
    traffic axis, frontier artifact with measured percentiles."""
    from fantoch_tpu.bote.validate import (
        check_frontier_artifact,
        frontier_candidates,
        validate_frontier,
    )

    planet = Planet.new()
    cands = frontier_candidates(planet, 3, 1)
    artifact, summary = validate_frontier(
        str(tmp_path / "bote"), planet=planet, candidates=cands,
        protocols=("fpaxos",), fs=(1,), conflicts=(100,),
        traffic=("flat", "churn"), commands=3, batch_lanes=4,
        segment_steps=512,
    )
    assert summary["done"], summary
    check_frontier_artifact(artifact)
    cand = artifact["candidates"][0]
    measured = cand["measured"]["fpaxos"]["f1"]
    for tname in ("flat", "churn"):
        stats = measured[tname]["100"]
        assert stats["count"] == 3 * 3  # commands × clients
        assert stats["errors"] == 0
        assert stats["p99"] >= stats["p50"] > 0

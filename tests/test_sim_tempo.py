"""Tempo whole-protocol simulation tests.

Mirrors fantoch_ps/src/protocol/mod.rs sim_tempo_* tests: with conflict-pool
workloads at 50% conflict, Tempo must be 100% fast path for (n, f) in
{(3,1), (5,1)} and take some slow paths for (5,2); real-time clock-bump mode
(tiny quorums) must also be 100% fast path for f=1.
"""

import pytest

from fantoch_tpu.core import Config
from fantoch_tpu.protocol.tempo import Tempo

from harness import sim_test


def tempo_config(n, f, clock_bump_interval_ms=None):
    config = Config(n=n, f=f, tempo_detached_send_interval_ms=100)
    if clock_bump_interval_ms is not None:
        config.tempo_tiny_quorums = True
        config.tempo_clock_bump_interval_ms = clock_bump_interval_ms
    return config


def test_sim_tempo_3_1():
    assert sim_test(Tempo, tempo_config(3, 1)) == 0


def test_sim_tempo_5_1():
    assert sim_test(Tempo, tempo_config(5, 1)) == 0


def test_sim_tempo_5_2():
    assert sim_test(Tempo, tempo_config(5, 2), seed=3) > 0


def test_sim_real_time_tempo_3_1():
    assert sim_test(Tempo, tempo_config(3, 1, clock_bump_interval_ms=50)) == 0


def test_sim_real_time_tempo_5_1():
    assert sim_test(Tempo, tempo_config(5, 1, clock_bump_interval_ms=50)) == 0


@pytest.mark.parametrize("seed", [1, 2, 4])
def test_sim_tempo_3_1_reorder_seeds(seed):
    """Reference-scale reorder runs across distinct seeds (the
    reference reruns its randomized sim_test on every CI invocation;
    fixed seeds keep ours deterministic while varying the schedules)."""
    assert sim_test(Tempo, tempo_config(3, 1), seed=seed) == 0

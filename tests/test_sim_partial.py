"""Partial replication (multi-shard) simulation tests.

The reference exercises partial replication only through its TCP
run-layer tests (fantoch/src/run/mod.rs:575-849; per-protocol cases in
fantoch_ps/src/protocol/mod.rs:251-399) — its DES is single-shard. Our
sim Runner supports shard_count > 1 directly (client-side result
aggregation + WAN-delayed cross-shard executor messages), so the same
invariants run deterministically:

- every client completes its budget (closed loop drains);
- per-shard linearizability-ish check: all n processes of a shard
  record identical per-key execution orders;
- commit accounting: each command commits once per touched shard, so
  total commits ∈ [cmds, cmds × shards]; stability is counted per
  command at its dot's (target) shard, so stable == n × cmds.
"""

import pytest

from fantoch_tpu.client import ConflictPool, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.protocol import Atlas, Tempo
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

COMMANDS = 10
CPR = 2  # clients per region


def run_partial(protocol_cls, n, f, shard_count, seed=0, reorder=True,
                **config_kw):
    config = Config(
        n=n,
        f=f,
        shard_count=shard_count,
        executor_monitor_execution_order=True,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        **config_kw,
    )
    planet = Planet.new()
    workload = Workload(
        shard_count=shard_count,
        key_gen=ConflictPool(conflict_rate=50, pool_size=1),
        keys_per_command=2,
        commands_per_client=COMMANDS,
        payload_size=1,
    )
    regions = planet.regions()[:n]
    runner = Runner(
        protocol_cls,
        planet,
        config,
        workload,
        CPR,
        regions,
        regions,
        seed=seed,
    )
    runner.reorder_messages = reorder
    metrics, monitors, latencies = runner.run(extra_sim_time_ms=10_000)

    total_cmds = COMMANDS * CPR * n
    issued = sum(v[0] for v in latencies.values())
    assert issued == total_cmds

    # per-shard execution-order equality
    for shard in range(shard_count):
        group = {
            pid: mon
            for pid, mon in monitors.items()
            if (pid - 1) // n == shard
        }
        assert len(group) == n
        items = list(group.items())
        pid_a, mon_a = items[0]
        for pid_b, mon_b in items[1:]:
            assert set(mon_a.keys()) == set(mon_b.keys())
            for key in mon_a.keys():
                assert mon_a.get_order(key) == mon_b.get_order(key), (
                    f"shard {shard}: order diverges on {key!r} between "
                    f"{pid_a} and {pid_b}"
                )

    fast = slow = stable = 0
    for pm, _em in metrics.values():
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
        stable += pm.get_aggregated(ProtocolMetricsKind.STABLE) or 0
    commits = fast + slow
    assert total_cmds <= commits <= total_cmds * shard_count
    # the reference counts stability per command at its target shard
    # (check_metrics, mod.rs:858-875: gc_at × commands == stable)
    assert stable == n * total_cmds, (stable, total_cmds)
    return commits


@pytest.mark.parametrize("shard_count", [2, 3])
def test_tempo_partial_replication(shard_count):
    run_partial(
        Tempo, 3, 1, shard_count, tempo_detached_send_interval_ms=100
    )


def test_tempo_partial_replication_n5(seed=1):
    run_partial(
        Tempo, 5, 2, 2, seed=seed, tempo_detached_send_interval_ms=100
    )


@pytest.mark.parametrize("shard_count", [2, 3])
def test_atlas_partial_replication(shard_count):
    run_partial(Atlas, 3, 1, shard_count)


def test_atlas_partial_replication_n5():
    run_partial(Atlas, 5, 2, 2)

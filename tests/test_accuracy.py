"""Accuracy milestone as a slow-tier pytest (VERDICT r5 weak #7).

``tools/accuracy.py`` asserts the ±2% device-vs-oracle latency
agreement on the BASELINE configs (EPaxos conflict sweep, Atlas vs
Tempo, the partial-replication twins) and renders the EuroSys'21-style
figures. It used to be a tool someone had to remember to run; as a
pytest it rides the slow tier (`pytest tests/ -m slow`) so the
milestone cannot silently regress.

Runs in a subprocess: the tool owns its JAX platform config and plot
output, and a crash must not poison this process's backend.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.mark.slow
def test_accuracy_milestone_quick():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "accuracy.py"),
         "--quick", "--cpu"],
        cwd=str(REPO),
        env=env,
        capture_output=True,
        text=True,
        timeout=3600,
    )
    assert proc.returncode == 0, (
        f"accuracy tool failed\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}"
    )
    # the report is the last JSON line on stdout
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    tol = report["tolerance"]
    assert report["epaxos_worst_rel_err"] <= tol
    assert report["atlas_tempo_worst_rel_err"] <= tol
    assert report["partial_worst_rel_err"] <= tol

"""Device-engine Tempo differential tests.

The array engine reproduces the host oracle *exactly* — per-region
latency means, fast/slow-path counts, GC stable totals — whenever the
schedule is tie-free. Under heavy same-instant concurrency the oracle's
recursive inline self-delivery sequences emissions mid-action, an order
a flat engine cannot reproduce in general; the reference itself treats
same-instant tie order as unspecified (fantoch/src/sim/schedule.rs:109-119
accepts either order), so for concurrent configs the engine defines its
own deterministic total order and the tests assert the protocol
invariants (commit totals, GC completeness) plus closeness of means.

Conflict rates are restricted to 0%/100% because anything in between
draws different PRNG streams host vs device.
"""

import pytest

from fantoch_tpu.client import ConflictPool, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.protocols import TempoDev
from fantoch_tpu.protocol import Tempo
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

COMMANDS = 30
CLIENTS_PER_REGION = 1


def tempo_config(n, f):
    return Config(
        n=n, f=f, gc_interval_ms=100, tempo_detached_send_interval_ms=100
    )


def run_oracle(config, regions, conflict, commands=COMMANDS,
               cpr=CLIENTS_PER_REGION):
    planet = Planet.new()
    workload = Workload(
        shard_count=1,
        key_gen=ConflictPool(conflict_rate=conflict, pool_size=1),
        keys_per_command=1,
        commands_per_client=commands,
        payload_size=0,
    )
    runner = Runner(
        Tempo,
        planet,
        config,
        workload,
        cpr,
        regions,
        list(regions),
    )
    metrics, _, latencies = runner.run(extra_sim_time_ms=1000)
    fast = slow = stable = 0
    for pm, _em in metrics.values():
        fast += pm.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        slow += pm.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
        stable += pm.get_aggregated(ProtocolMetricsKind.STABLE) or 0
    return latencies, fast, slow, stable


def run_engine(config, regions, conflict, commands=COMMANDS,
               cpr=CLIENTS_PER_REGION):
    planet = Planet.new()
    clients = cpr * len(regions)
    tempo = TempoDev(keys=1 + clients)
    total = commands * clients
    dims = EngineDims.for_protocol(
        tempo,
        n=config.n,
        clients=clients,
        payload=tempo.payload_width(config.n),
        total_commands=total,
        dot_slots=total + 1,
        regions=len(regions),
    )
    spec = make_lane(
        tempo,
        planet,
        config,
        conflict_rate=conflict,
        pool_size=1,
        commands_per_client=commands,
        clients_per_region=cpr,
        process_regions=regions,
        client_regions=regions,
        dims=dims,
    )
    return tempo, run_lanes(tempo, dims, [spec])[0]


@pytest.mark.parametrize(
    "n,f,conflict,commands,cpr",
    [
        (3, 1, 100, 30, 2),
        (3, 1, 0, 30, 2),
        (5, 1, 100, 10, 1),
        (5, 2, 100, 20, 1),
        # reference sim_test scale (mod.rs:639-705: 100 commands)
        pytest.param(3, 1, 100, 100, 2, marks=pytest.mark.slow),
        pytest.param(5, 2, 100, 100, 1, marks=pytest.mark.slow),
    ],
)
def test_engine_tempo_matches_oracle_exactly(n, f, conflict, commands, cpr):
    """Tie-free schedules: every metric matches the oracle exactly."""
    config = tempo_config(n, f)
    regions = Planet.new().regions()[:n]
    oracle_lat, fast, slow, stable = run_oracle(
        config, regions, conflict, commands, cpr
    )
    _tempo, res = run_engine(config, regions, conflict, commands, cpr)
    assert not res.err
    assert int(res.protocol_metrics["fast_path"].sum()) == fast
    assert int(res.protocol_metrics["slow_path"].sum()) == slow
    assert int(res.protocol_metrics["stable"].sum()) == stable
    for region in regions:
        _issued, hist = oracle_lat[region]
        assert res.latency_mean(region) == hist.mean(), region
    # reference expectation: f=1 is 100% fast path
    # (fantoch_ps/src/protocol/mod.rs:116-147)
    if f == 1:
        assert slow == 0


def test_engine_tempo_concurrent_invariants():
    """Same-instant concurrency: tie orders legitimately differ from the
    oracle (unspecified in the reference too), so assert the protocol
    invariants and that latency means stay close."""
    n, f, conflict, commands, cpr = 5, 1, 100, 30, 2
    config = tempo_config(n, f)
    regions = Planet.new().regions()[:n]
    oracle_lat, fast, slow, stable = run_oracle(
        config, regions, conflict, commands, cpr
    )
    _tempo, res = run_engine(config, regions, conflict, commands, cpr)
    assert not res.err
    total_commits = commands * cpr * n
    dev_fast = int(res.protocol_metrics["fast_path"].sum())
    dev_slow = int(res.protocol_metrics["slow_path"].sum())
    assert dev_fast + dev_slow == total_commits == fast + slow
    assert dev_slow == 0  # f=1 ⇒ 100% fast path
    assert int(res.protocol_metrics["stable"].sum()) == n * total_commits
    for region in regions:
        _issued, hist = oracle_lat[region]
        assert res.issued(region) == commands * cpr
        assert abs(res.latency_mean(region) - hist.mean()) <= 0.1 * hist.mean()


def test_engine_tempo_skip_fast_ack_matches_oracle():
    """skip_fast_ack (tempo.rs:91-93, 330-335, 442-455): with a pair
    fast quorum the non-coordinator member commits directly from the
    MCollect, skipping the ack round. Device twin must match the host
    oracle exactly — and beat the normal path's latency."""
    n, f, conflict, commands, cpr = 3, 1, 100, 20, 1
    regions = Planet.new().regions()[:n]

    def both(skip):
        config = Config(
            n=n, f=f, gc_interval_ms=100,
            tempo_detached_send_interval_ms=100,
            skip_fast_ack=skip,
        )
        lat, fast, slow, stable = run_oracle(
            config, regions, conflict, commands, cpr
        )
        planet = Planet.new()
        clients = cpr * n
        tempo = TempoDev(keys=1 + clients, skip_capable=skip)
        total = commands * clients
        dims = EngineDims.for_protocol(
            tempo,
            n=n,
            clients=clients,
            payload=tempo.payload_width(n),
            total_commands=total,
            dot_slots=total + 1,
            regions=n,
        )
        spec = make_lane(
            tempo,
            planet,
            config,
            conflict_rate=conflict,
            pool_size=1,
            commands_per_client=commands,
            clients_per_region=cpr,
            process_regions=regions,
            client_regions=regions,
            dims=dims,
        )
        res = run_lanes(tempo, dims, [spec])[0]
        assert not res.err, res.err_cause
        return lat, fast, slow, stable, res

    lat, fast, slow, stable, res = both(skip=True)
    total = commands * cpr * n
    # the skip path records no fast/slow classification — neither side
    # counts, but GC still accounts for every commit
    assert fast == slow == 0
    assert int(res.protocol_metrics["fast_path"].sum()) == 0
    assert int(res.protocol_metrics["slow_path"].sum()) == 0
    assert int(res.protocol_metrics["stable"].sum()) == stable == n * total
    for region in regions:
        _issued, hist = lat[region]
        assert res.latency_mean(region) == hist.mean(), region

    # sanity: skipping the ack round can only help latency
    lat_off, _, _, _, res_off = both(skip=False)
    for region in regions:
        assert res.latency_mean(region) <= res_off.latency_mean(region)

"""Smoke tests for the CLI surface (fantoch_ps/src/bin analogs).

proc/client are exercised end-to-end by test_exp.py; here the
remaining subcommands — sim, sweep, bote, plot — run in-process with
``--platform cpu`` so the suite passes with no device present
(the reference's binaries are likewise runnable anywhere).
"""

import json

import pytest

from fantoch_tpu.cli import main


def _run(capsys, *argv):
    main(list(argv))
    return capsys.readouterr().out


def test_cli_sim(capsys):
    out = _run(
        capsys,
        "--platform", "cpu",
        "sim",
        "--protocol", "basic",
        "--n", "3",
        "--f", "1",
        "--commands", "5",
        "--conflict", "0",
    )
    data = json.loads(out)
    assert data["protocol"] == "basic"
    assert len(data["regions"]) == 3
    for stats in data["regions"].values():
        assert stats["issued"] == 5
        assert stats["mean_ms"] > 0


def test_cli_sweep_and_plot(capsys, tmp_path):
    results = str(tmp_path / "sweep.jsonl")
    out = _run(
        capsys,
        "--platform", "cpu",
        "sweep",
        "--protocol", "fpaxos",
        "--n", "3",
        "--fs", "1",
        "--conflicts", "0,100",
        "--subsets", "2",
        "--commands", "5",
        "--out", results,
    )
    data = json.loads(out)
    assert data["points"] == 4
    assert data["errors"] == 0

    png = str(tmp_path / "out.png")
    out = _run(
        capsys,
        "--platform", "cpu",
        "plot",
        "--results", results,
        "--kind", "cdf",
        "--match", "conflict=0",
        "--out", png,
    )
    data = json.loads(out)
    assert data["plotted"] == 2
    assert (tmp_path / "out.png").stat().st_size > 0


def test_cli_sweep_faults(capsys, tmp_path):
    """--faults replicates each sweep point per plan (fault-free +
    crash + partition in ONE compiled sweep) and surfaces per-lane
    fault metadata in the summary and the saved results."""
    results = str(tmp_path / "faults.jsonl")
    out = _run(
        capsys,
        "--platform", "cpu",
        "sweep",
        "--protocol", "basic",
        "--n", "3",
        "--fs", "1",
        "--conflicts", "100",
        "--subsets", "1",
        "--commands", "5",
        "--faults",
        '[{}, {"crash": {"2": 100}}, '
        '{"windows": [{"src": 0, "dst": 1, "t0": 0, "t1": 300, '
        '"delay": "inf"}], "horizon": 3000}]',
        "--out", results,
    )
    data = json.loads(out)
    assert data["points"] == 3
    assert data["fault_lanes"] == 2
    assert data["unavailable_lanes"] == 0
    assert data["errors"] == 0

    rows = [json.loads(line) for line in open(results)]
    metas = [r["attrs"].get("faults") for r in rows]
    assert sum(m is None for m in metas) == 1
    assert any(m and "crash" in m for m in metas)
    assert any(m and "windows" in m for m in metas)


def test_cli_bote(capsys):
    out = _run(
        capsys,
        "--platform", "cpu",
        "bote",
        "--min-n", "3",
        "--max-n", "3",
        "--top", "1",
    )
    data = json.loads(out)
    assert "3" in data or 3 in data


def test_cli_platform_tpu_fail_fast(monkeypatch):
    """--platform tpu exits with a clear message when the probe fails."""
    import fantoch_tpu.cli as cli

    monkeypatch.setattr(cli, "_probe_backend", lambda t: False)
    with pytest.raises(SystemExit) as exc:
        main(["--platform", "tpu", "sweep", "--protocol", "basic"])
    assert "unreachable" in str(exc.value)


def test_cli_platform_auto_host_only_never_probes(capsys, monkeypatch):
    """Host-only subcommands never touch the device backend."""
    import fantoch_tpu.cli as cli

    def boom(t):  # pragma: no cover - must not be called
        raise AssertionError("probe ran for a host-only subcommand")

    monkeypatch.setattr(cli, "_probe_backend", boom)
    out = _run(
        capsys,
        "sim",
        "--protocol", "basic",
        "--n", "3",
        "--f", "0",
        "--commands", "2",
        "--conflict", "0",
    )
    assert json.loads(out)["slow_path"] == 0


def test_cli_sweep_partial_replication(capsys):
    """--shards routes the sweep through the multi-shard device twins
    (TempoPartialDev/AtlasPartialDev); unsupported protocols fail with
    a clear message like the reference's partial.rs coverage."""
    out = _run(
        capsys,
        "--platform", "cpu",
        "sweep",
        "--protocol", "tempo",
        "--n", "3",
        "--shards", "2",
        "--fs", "1",
        "--conflicts", "100",
        "--pool-size", "4",
        "--subsets", "1",
        "--commands", "4",
    )
    data = json.loads(out)
    assert data["points"] == 1 and data["errors"] == 0

    with pytest.raises(SystemExit) as exc:
        main(
            [
                "--platform", "cpu",
                "sweep",
                "--protocol", "caesar",
                "--n", "3",
                "--shards", "2",
            ]
        )
    assert "partial replication" in str(exc.value)

"""Heterogeneous megabatch engine (engine/hetero.py, run_sweep(hetero=True),
campaign mixed units, fleet one-executable layout).

The contracts under test:

* a mixed (protocol-switched) batch produces **byte-identical**
  ``LaneResults`` to each lane's homogeneous control — through the
  ``protocol_id``-routed ``lax.switch`` over skeleton-packed state, the
  packed liveness views, and the grid-narrowing seam — composing with
  ``scan_window``, ``pipeline_depth`` and checkpoints;
* a single-protocol mixed batch matches the native path byte-exactly
  (the alpha-equivalence property GL005/GL605 prove at trace level,
  pinned here at the results level);
* checkpoint manifests carry the skeleton fingerprint: a foreign-grid
  resume and a mixed<->homogeneous interchange are refused BY NAME
  (``skeleton`` / ``kind``), never silently misloaded;
* ONE AOT slot serves every composition of a grid skeleton — two
  permuted mixed batches share one serialized executable and stay
  byte-identical to their controls;
* ``hetero: true`` campaigns write a ``results.jsonl`` byte-identical
  to the homogeneous layout (manager, interrupted+resumed, and the
  fleet-worker + merge path), with exactly one ``aot/exe-*.bin``;
* refusals: ``stack_lanes`` on structure-mixed lanes, slashed group
  keys (the checkpoint flattener's separator), monitored batches,
  ``mesh_shard``/2-D sharded layouts, bare-string skeletons, and
  ``hetero`` x ``mesh_shard`` campaign specs — all by name;
* ``hetero_plan``/``hetero_regroup`` are pure functions of
  (spec, batches): always-full units, pad rows dropped, the inverse
  permutation hole-free; ``rank_points(composition=...)`` rebalances
  steering toward under-represented protocols and ``None`` keeps the
  legacy order byte-stable.

Tier-1 pins basic + tempo at the engine layer plus every host-only
contract; the full single-shard protocol matrix and the campaign /
fleet / AOT-slot pins ride in the slow tier — the CI ``hetero-smoke``
job re-runs the campaign/fleet byte-identity story (with a real
kill -9) on every push, so tier-1 stays inside its wall-clock budget
without losing the pin. The sharded variants (tempo/atlas @2shards) are
deliberately absent: ``hetero=True`` refuses ``mesh_shard`` and
``state_shards > 1`` (pinned below) — sharded grids run homogeneous.
"""

import glob
import json
import os

import pytest

from fantoch_tpu.campaign import (
    CampaignError,
    campaign_from_json,
    run_campaign,
)
from fantoch_tpu.campaign.manager import (
    _sweep_batches,
    hetero_plan,
    hetero_regroup,
)
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane
from fantoch_tpu.engine import hetero as hetero_mod
from fantoch_tpu.engine.checkpoint import (
    CheckpointMismatchError,
    CheckpointSpec,
    SweepInterrupted,
    canonical_json,
)
from fantoch_tpu.engine.hetero import HeteroBatchError
from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol
from fantoch_tpu.engine.spec import stack_lanes
from fantoch_tpu.fleet import merge_campaign, run_fleet_worker
from fantoch_tpu.mc.coverage import rank_points
from fantoch_tpu.parallel.sweep import run_sweep
from fantoch_tpu.registry import DEV_PROTOCOLS

COMMANDS = 2
MAX = 1 << 20

# mirrors tests/test_campaign.py SWEEP_GRID (plus tempo + aot) so the
# campaign units reuse the suite's compiled runners; scan_window=1 pins
# the per-segment ladder the interruption tests count on
HETERO_GRID = {
    "kind": "sweep",
    "protocols": ["basic", "tempo"],
    "ns": [3],
    "conflicts": [0, 100],
    "subsets": 2,
    "commands_per_client": 2,
    "batch_lanes": 2,
    "segment_steps": 8,
    "scan_window": 1,
    "aot": True,
}


def _build(name: str, conflict: int = 100):
    planet = Planet.new()
    regions = planet.regions()[:3]
    clients = 3
    total = COMMANDS * clients
    dev = dev_protocol(name, clients)
    config = Config(**dev_config_kwargs(name, 3, 1))
    dims = EngineDims.for_protocol(
        dev, n=3, clients=clients, payload=dev.payload_width(3),
        total_commands=total, dot_slots=total + 1, regions=3,
    )
    spec = make_lane(
        dev, planet, config, conflict_rate=conflict, pool_size=1,
        commands_per_client=COMMANDS, clients_per_region=1,
        process_regions=regions, client_regions=regions, dims=dims,
    )
    return dev, dims, spec


def _grid(names=("basic", "tempo")):
    """(protocols, dims, specs) maps over ``names``, two conflict
    points each, plus the canonical interleaved mixed lane list."""
    protocols, dims, specs = {}, {}, {}
    for name in names:
        dev, d, s100 = _build(name)
        _, _, s0 = _build(name, conflict=0)
        protocols[name], dims[name], specs[name] = dev, d, [s100, s0]
    mixed = []
    for i in range(2):
        for name in names:
            mixed.append((name, specs[name][i]))
    return protocols, dims, specs, mixed


def _blob(r) -> str:
    return canonical_json(r.to_json())


def _controls(protocols, dims, specs, **kw):
    return {
        name: run_sweep(protocols[name], dims[name], specs[name],
                        max_steps=MAX, **kw)
        for name in protocols
    }


# ----------------------------------------------------------------------
# mixed == homogeneous, byte-exact
# ----------------------------------------------------------------------


def test_mixed_batch_byte_identical_to_homogeneous():
    protocols, dims, specs, mixed = _grid()
    res = run_sweep(protocols, dims, mixed, hetero=True,
                    max_steps=MAX, segment_steps=4096)
    ctrl = _controls(protocols, dims, specs, segment_steps=4096)
    for mi, (name, _) in enumerate(mixed):
        ci = mi // len(protocols)
        assert _blob(res[mi]) == _blob(ctrl[name][ci]), (
            f"mixed lane {mi} ({name}) diverged from its homogeneous "
            "control"
        )


def test_single_protocol_hetero_matches_native():
    # the GL005/GL605 alpha-equivalence property at the results level:
    # routing a homogeneous batch through the protocol_id switch
    # changes nothing about any lane's arithmetic
    protocols, dims, specs, _ = _grid(("basic",))
    res = run_sweep(protocols, dims,
                    [("basic", s) for s in specs["basic"]],
                    hetero=True, max_steps=MAX, segment_steps=4096)
    native = run_sweep(protocols["basic"], dims["basic"], specs["basic"],
                       max_steps=MAX, segment_steps=4096)
    assert [_blob(r) for r in res] == [_blob(r) for r in native]


@pytest.mark.slow
def test_all_protocols_mixed_byte_identical():
    # every single-shard dev protocol through ONE switch; the sharded
    # audits are excluded by construction (hetero refuses mesh_shard /
    # state_shards > 1 — pinned in test_run_sweep_hetero_refusals)
    protocols, dims, specs, mixed = _grid(tuple(DEV_PROTOCOLS))
    res = run_sweep(protocols, dims, mixed, hetero=True,
                    max_steps=MAX, segment_steps=4096)
    ctrl = _controls(protocols, dims, specs, segment_steps=4096)
    for mi, (name, _) in enumerate(mixed):
        ci = mi // len(protocols)
        assert _blob(res[mi]) == _blob(ctrl[name][ci])


# ----------------------------------------------------------------------
# composition: windows x pipeline x checkpoints
# ----------------------------------------------------------------------


def test_hetero_composes_with_windows_and_pipeline():
    protocols, dims, specs, mixed = _grid()
    base = run_sweep(protocols, dims, mixed, hetero=True,
                     max_steps=MAX, segment_steps=4096)
    want = [_blob(r) for r in base]
    for kw in (
        {"segment_steps": 64, "scan_window": 1},
        {"segment_steps": 64, "scan_window": 4},
        {"segment_steps": 64, "scan_window": 1, "pipeline_depth": 1},
    ):
        got = run_sweep(protocols, dims, mixed, hetero=True,
                        max_steps=MAX, **kw)
        assert [_blob(r) for r in got] == want, f"diverged under {kw}"


def test_hetero_checkpoint_interrupt_resume_byte_identical(tmp_path):
    protocols, dims, specs, mixed = _grid()
    base = run_sweep(protocols, dims, mixed, hetero=True,
                     max_steps=MAX, segment_steps=4096)
    ck = str(tmp_path / "ck.npz")
    with pytest.raises(SweepInterrupted):
        run_sweep(protocols, dims, mixed, hetero=True, max_steps=MAX,
                  segment_steps=16, scan_window=1,
                  checkpoint=CheckpointSpec(path=ck,
                                            stop_after_segments=1))
    res = run_sweep(protocols, dims, mixed, hetero=True, max_steps=MAX,
                    segment_steps=16, scan_window=1,
                    checkpoint=CheckpointSpec(path=ck))
    assert [_blob(r) for r in res] == [_blob(r) for r in base]


def test_foreign_skeleton_and_layout_interchange_refused(tmp_path):
    protocols, dims, specs, mixed = _grid()
    ck = str(tmp_path / "ck.npz")
    with pytest.raises(SweepInterrupted):
        run_sweep(protocols, dims, mixed, hetero=True, max_steps=MAX,
                  segment_steps=16, scan_window=1,
                  checkpoint=CheckpointSpec(path=ck,
                                            stop_after_segments=1))

    # a WIDER grid skeleton (+fpaxos) is a different union state — the
    # manifest's fingerprint refuses the resume by name
    p3, d3, s3, _ = _grid(("basic", "tempo", "fpaxos"))
    skel, nspec = hetero_mod.build_grid_skeleton(
        p3, d3, {name: s3[name][0] for name in p3}, batch_lanes=4)
    with pytest.raises(CheckpointMismatchError, match="skeleton"):
        run_sweep(p3, d3, mixed, hetero=True, skeleton=skel,
                  narrow=nspec, max_steps=MAX, segment_steps=16,
                  scan_window=1, checkpoint=CheckpointSpec(path=ck))

    # mixed -> homogeneous interchange: the native runner refuses the
    # packed artifact by kind (and vice versa below)
    with pytest.raises(CheckpointMismatchError, match="kind"):
        run_sweep(protocols["basic"], dims["basic"],
                  [specs["basic"][0]] * 4, max_steps=MAX,
                  segment_steps=16, scan_window=1,
                  checkpoint=CheckpointSpec(path=ck))

    ck2 = str(tmp_path / "ck2.npz")
    with pytest.raises(SweepInterrupted):
        run_sweep(protocols["basic"], dims["basic"],
                  [specs["basic"][0]] * 4, max_steps=MAX,
                  segment_steps=16, scan_window=1,
                  checkpoint=CheckpointSpec(path=ck2,
                                            stop_after_segments=1))
    with pytest.raises(CheckpointMismatchError, match="kind"):
        run_sweep(protocols, dims,
                  [("basic", specs["basic"][0])] * 4, hetero=True,
                  max_steps=MAX, segment_steps=16, scan_window=1,
                  checkpoint=CheckpointSpec(path=ck2))


# ----------------------------------------------------------------------
# one AOT slot per grid skeleton
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_one_aot_slot_serves_permuted_compositions(tmp_path):
    protocols, dims, specs, mixed = _grid()
    base = run_sweep(protocols, dims, mixed, hetero=True,
                     max_steps=MAX, segment_steps=4096)
    skel, nspec = hetero_mod.build_grid_skeleton(
        protocols, dims,
        {name: specs[name][0] for name in protocols}, batch_lanes=4)
    aot_dir = str(tmp_path / "aot")
    # mixed2[i] == mixed[perm[i]] — a different composition of the
    # same grid must hit the SAME serialized executable
    perm = [1, 3, 0, 2]
    mixed2 = [mixed[i] for i in perm]
    r1 = run_sweep(protocols, dims, mixed, hetero=True, skeleton=skel,
                   narrow=nspec, max_steps=MAX, segment_steps=4096,
                   aot=aot_dir)
    r2 = run_sweep(protocols, dims, mixed2, hetero=True, skeleton=skel,
                   narrow=nspec, max_steps=MAX, segment_steps=4096,
                   aot=aot_dir)
    exes = glob.glob(os.path.join(aot_dir, "exe-*.bin"))
    assert len(exes) == 1, f"expected one executable, got {exes}"
    assert [_blob(r) for r in r1] == [_blob(r) for r in base]
    assert [_blob(r) for r in r2] == [_blob(base[i]) for i in perm]


# ----------------------------------------------------------------------
# refusals, by name
# ----------------------------------------------------------------------


def test_stack_lanes_refuses_structure_mixed_lanes():
    _, _, b = _build("basic")
    _, _, t = _build("tempo")
    with pytest.raises(AssertionError, match="cannot share a batch"):
        stack_lanes([b, t])


def test_run_sweep_hetero_refusals():
    protocols, dims, specs, mixed = _grid()
    with pytest.raises(ValueError, match="mesh_shard"):
        run_sweep(protocols, dims, mixed, hetero=True, max_steps=MAX,
                  segment_steps=64, mesh_shard=True)
    with pytest.raises(ValueError, match="state-sharded"):
        run_sweep(protocols, dims, mixed, hetero=True, max_steps=MAX,
                  segment_steps=64, state_shards=2)
    with pytest.raises(ValueError, match="bare fingerprint"):
        run_sweep(protocols, dims, mixed, hetero=True, max_steps=MAX,
                  segment_steps=64, skeleton="deadbeef" * 8)
    with pytest.raises(HeteroBatchError, match="monitor"):
        run_sweep(protocols, dims, mixed, hetero=True, max_steps=MAX,
                  segment_steps=64, monitor_keys=2)


def test_slashed_group_key_refused_by_name():
    # '/' is the checkpoint flattener's path separator — a packed
    # state keyed by it would not survive a manifest round trip
    protocols, dims, specs, _ = _grid(("basic",))
    with pytest.raises(HeteroBatchError, match="flattener"):
        hetero_mod.prepare_batch(
            {"basic/n3": protocols["basic"]},
            {"basic/n3": dims["basic"]},
            [("basic/n3", specs["basic"][0])],
        )


def test_campaign_refuses_hetero_mesh_shard():
    with pytest.raises(CampaignError, match="hetero"):
        campaign_from_json(
            dict(HETERO_GRID, aot=False, hetero=True, mesh_shard=True))


# ----------------------------------------------------------------------
# mixed-unit packing: plan/regroup purity
# ----------------------------------------------------------------------


def test_hetero_plan_full_units_and_regroup_inverts():
    spec = campaign_from_json(dict(HETERO_GRID, hetero=True))
    batches = _sweep_batches(spec)
    protos, dmap, reps, units, positions = hetero_plan(spec, batches)
    again = hetero_plan(spec, batches)
    assert [k for k, _ in units] == [k for k, _ in again[3]]
    assert positions == again[4], "hetero_plan must be deterministic"

    B = spec.batch_lanes
    total = sum(len(lanes) for _, _, _, lanes in batches)
    assert all(len(lanes) == B for _, lanes in units), (
        "every mixed unit must be packed full (the last one padded)"
    )
    assert sum(len(v) for v in positions.values()) == total, (
        "positions must index exactly the real (unpadded) rows"
    )
    assert all(k.startswith("hetero/b") for k, _ in units)
    # group keys that reach the packed state are '/'-free
    assert all("/" not in g for g, _ in units[0][1])

    # regroup inverts the permutation: synthesize per-unit rows that
    # name their origin, then demand the homogeneous layout back
    done = {
        k: [json.dumps([k, i]) for i in range(len(positions[k]))]
        for k, _ in units
    }
    by_batch = hetero_regroup(batches, units, positions, done)
    assert sorted(by_batch) == sorted(k for k, _, _, lanes in batches)
    flat = [r for k, _, _, lanes in batches for r in by_batch[k]]
    assert len(flat) == total and all(r is not None for r in flat)

    # a torn journal (one row short) is a named error, not a hole
    short = dict(done)
    first = units[0][0]
    short[first] = done[first][:-1]
    with pytest.raises(CampaignError, match="journal"):
        hetero_regroup(batches, units, positions, short)


# ----------------------------------------------------------------------
# campaign / fleet byte-identity
# ----------------------------------------------------------------------


def _results_bytes(path: str) -> bytes:
    with open(os.path.join(path, "results.jsonl"), "rb") as fh:
        return fh.read()


@pytest.mark.slow
def test_hetero_campaign_byte_identical_one_executable(tmp_path):
    homo = str(tmp_path / "homo")
    ctrl = run_campaign(homo, campaign_from_json(HETERO_GRID))
    assert ctrl["done"] and ctrl["errors"] == 0

    het = str(tmp_path / "het")
    summary = run_campaign(
        het, campaign_from_json(dict(HETERO_GRID, hetero=True)))
    assert summary["done"] and summary["errors"] == 0

    control = _results_bytes(homo)
    assert control and _results_bytes(het) == control

    # the whole mixed grid compiled into ONE serialized executable;
    # the homogeneous layout needs one per protocol
    assert len(glob.glob(os.path.join(het, "aot", "exe-*.bin"))) == 1
    assert len(glob.glob(os.path.join(homo, "aot", "exe-*.bin"))) == 2

    # interrupted + resumed, still byte-identical
    intr = str(tmp_path / "intr")
    s1 = run_campaign(intr,
                      campaign_from_json(dict(HETERO_GRID, hetero=True)),
                      stop_after_segments=1)
    assert not s1["done"]
    s2 = run_campaign(intr, resume=True)
    assert s2["done"]
    assert _results_bytes(intr) == control


@pytest.mark.slow
def test_hetero_fleet_merge_byte_identical(tmp_path):
    homo = str(tmp_path / "homo")
    run_fleet_worker(homo, campaign_from_json(HETERO_GRID),
                     worker_id="w1")
    assert merge_campaign(homo)["merged"]
    control = _results_bytes(homo)
    assert control

    fleet = str(tmp_path / "fleet")
    spec = campaign_from_json(dict(HETERO_GRID, hetero=True))
    run_fleet_worker(fleet, spec, worker_id="w1", stop_after_units=1)
    run_fleet_worker(fleet, None, worker_id="w2")
    assert merge_campaign(fleet)["merged"]
    assert _results_bytes(fleet) == control


# ----------------------------------------------------------------------
# skeleton-aware steering
# ----------------------------------------------------------------------


def test_rank_points_composition_rebalances():
    points = [("basic", 3), ("tempo", 3), ("atlas", 3)]
    # all tried equally, none starved, identical discovery rates —
    # the legacy order is the canonical enumeration
    progress = {
        "basic/n3": {"tried": 5, "cov_recent": [[5, 2]]},
        "tempo/n3": {"tried": 5, "cov_recent": [[5, 2]]},
        "atlas/n3": {"tried": 5, "cov_recent": [[5, 2]]},
    }
    legacy = rank_points(points, progress, schedules=10)
    assert legacy == ["basic/n3", "tempo/n3", "atlas/n3"]
    assert rank_points(points, progress, schedules=10,
                       composition=None) == legacy

    # a mixed batch over-full of basic: under-represented protocols
    # rank first among the unstarved
    ranked = rank_points(points, progress, schedules=10,
                         composition={"basic": 3, "tempo": 1})
    assert ranked == ["atlas/n3", "tempo/n3", "basic/n3"]

    # starvation still dominates composition
    progress["basic/n3"] = {"tried": 0}
    ranked = rank_points(points, progress, schedules=10,
                         composition={"basic": 3, "tempo": 1})
    assert ranked[0] == "basic/n3"

    # determinism: pure function of its (journaled) inputs
    assert ranked == rank_points(points, dict(progress), schedules=10,
                                 composition={"basic": 3, "tempo": 1})


# ----------------------------------------------------------------------
# GL605 (slow: compiles and executes three runners)
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_gl605_selfcheck_fires():
    from fantoch_tpu.lint.skeleton import (
        check_mixed_batch,
        run_skeleton_selfcheck,
    )

    assert check_mixed_batch() == []
    findings, meta = run_skeleton_selfcheck("mixed")
    assert findings and all(f.rule == "GL605" for f in findings)

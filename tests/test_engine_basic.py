"""Device-engine differential tests: Basic protocol on the vmapped JAX
engine must reproduce the reference's deterministic sim expectations
(fantoch/src/sim/runner.rs:818-870) — the same numbers the host oracle
reproduces in test_sim_basic.py — with several configs advancing in one
batch.
"""

import numpy as np
import pytest

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.protocols import BasicDev

COMMANDS_PER_CLIENT = 100
PROCESS_REGIONS = ["asia-east1", "us-central1", "us-west1"]
CLIENT_REGIONS = ["us-west1", "us-west2"]


def make_specs(fs, clients_per_region=1, commands=COMMANDS_PER_CLIENT):
    planet = Planet.new()
    clients = clients_per_region * len(CLIENT_REGIONS)
    dims = EngineDims.for_protocol(
        BasicDev,
        n=3,
        clients=clients,
        payload=BasicDev.payload_width(3),
        total_commands=commands * clients,
        dot_slots=commands * clients + 1,
        regions=len(CLIENT_REGIONS),
    )
    specs = [
        make_lane(
            BasicDev,
            planet,
            Config(n=3, f=f, gc_interval_ms=100),
            conflict_rate=100,
            pool_size=1,
            commands_per_client=commands,
            clients_per_region=clients_per_region,
            process_regions=PROCESS_REGIONS,
            client_regions=CLIENT_REGIONS,
            dims=dims,
            extra_time_ms=1000,
        )
        for f in fs
    ]
    return dims, specs


def test_engine_matches_reference_latency_means():
    """One batch sweeping f ∈ {0,1,2}; exact reference means
    (runner.rs:832-848)."""
    dims, specs = make_specs([0, 1, 2])
    results = run_lanes(BasicDev, dims, specs)
    expected = {0: (0.0, 24.0), 1: (34.0, 58.0), 2: (118.0, 142.0)}
    for f, res in zip([0, 1, 2], results):
        assert not res.err
        mean1, mean2 = expected[f]
        assert res.issued("us-west1") == COMMANDS_PER_CLIENT
        assert res.issued("us-west2") == COMMANDS_PER_CLIENT
        assert res.latency_mean("us-west1") == mean1
        assert res.latency_mean("us-west2") == mean2
        # all commands garbage-collected at every process
        # (check_metrics, fantoch_ps/src/protocol/mod.rs:858-875)
        total = COMMANDS_PER_CLIENT * len(CLIENT_REGIONS)
        stable = res.protocol_metrics["stable"]
        assert list(stable) == [total] * 3


def test_engine_latency_independent_of_client_count():
    """runner.rs:851-870: stats don't change with more clients."""
    dims1, specs1 = make_specs([1], clients_per_region=1, commands=50)
    one = run_lanes(BasicDev, dims1, specs1)[0]
    dims10, specs10 = make_specs([1], clients_per_region=10, commands=50)
    ten = run_lanes(BasicDev, dims10, specs10)[0]
    assert not one.err and not ten.err
    for region in CLIENT_REGIONS:
        assert one.latency_mean(region) == ten.latency_mean(region)
        h1, h10 = one.histogram(region), ten.histogram(region)
        assert h1.cov() == h10.cov()


def test_engine_matches_host_oracle():
    """Differential check against the host oracle runner on an AWS
    planet (different latencies than the hand-checked GCP numbers)."""
    from fantoch_tpu.client import ConflictPool, Workload
    from fantoch_tpu.protocol import Basic
    from fantoch_tpu.sim import Runner

    planet = Planet.from_dataset("latency_aws_2021_02_13")
    regions = planet.regions()[:3]
    client_regions = regions[:2]
    config = Config(n=3, f=1, gc_interval_ms=100)

    workload = Workload(
        shard_count=1,
        key_gen=ConflictPool(conflict_rate=100, pool_size=1),
        keys_per_command=1,
        commands_per_client=50,
        payload_size=0,
    )
    runner = Runner(
        Basic, planet, config, workload, 1, list(regions), list(client_regions)
    )
    _, _, oracle_latencies = runner.run(extra_sim_time_ms=1000)

    dims = EngineDims.for_protocol(
        BasicDev,
        n=3,
        clients=2,
        payload=BasicDev.payload_width(3),
        total_commands=100,
        dot_slots=101,
        regions=2,
    )
    spec = make_lane(
        BasicDev,
        planet,
        config,
        conflict_rate=100,
        pool_size=1,
        commands_per_client=50,
        clients_per_region=1,
        process_regions=regions,
        client_regions=client_regions,
        dims=dims,
    )
    res = run_lanes(BasicDev, dims, [spec])[0]
    assert not res.err
    for region in client_regions:
        _issued, hist = oracle_latencies[region]
        assert res.latency_mean(region) == hist.mean()

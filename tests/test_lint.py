"""graft-lint tests (fantoch_tpu/lint): interval-analysis units on
synthetic jaxprs, alpha-equivalence units, the two seeded regressions
the CI contract demands (an unclamped i32 multiply reachable from a
protocol step, and a protocol registered without its monitor hooks),
AST-rule fixtures, and the CLI gate's exit behavior."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane
from fantoch_tpu.engine.core import cumsum_i32, init_lane_state
from fantoch_tpu.engine.dims import INF
from fantoch_tpu.engine.protocols import BasicDev, dev_config_kwargs
from fantoch_tpu.lint import DEFAULT_BASELINE, load_baseline
from fantoch_tpu.lint.gating import alpha_equivalent, check_gating
from fantoch_tpu.lint.jaxpr import audit_fn, audit_trace, trace_step
from fantoch_tpu.lint.rules import check_protocol_hooks, run_ast_rules

I32 = jnp.int32
FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "lint_bad.py")


# ----------------------------------------------------------------------
# interval analysis units (synthetic jaxprs)
# ----------------------------------------------------------------------


def test_unclamped_mul_flagged():
    def f(x):
        return x * 70001 * 70001

    fs = audit_fn(f, np.int32(3), seeds={"0": (0, 1 << 20)})
    assert any(g.rule == "GL001" and ":mul" in g.anchor for g in fs), fs


def test_clamped_mul_clean():
    """The PR-1 idiom: `where(mul would overflow, INF, x * mul)` — the
    select's predicate reads the multiplicands, so the escape is
    recognized as guarded."""

    def f(x, m):
        cap = INF // jnp.maximum(x, 1)
        return jnp.where(m > cap, INF, x * m)

    fs = audit_fn(
        f, np.int32(3), np.int32(5),
        seeds={"0": (0, 1 << 24), "1": (0, 1 << 24)},
    )
    assert fs == [], [g.render() for g in fs]


def test_masked_write_is_not_a_guard():
    """A lane-select whose predicate ignores the product must NOT count
    as a clamp (the guard check is pred-linked, not any-select)."""

    def f(x, arr, i):
        big = x * 70001 * 70001
        hit = jnp.arange(arr.shape[0], dtype=I32) == i
        return jnp.where(hit, big, arr)

    fs = audit_fn(
        f, np.int32(3), np.zeros((4,), np.int32), np.int32(1),
        seeds={"0": (0, 1 << 20), "1": (0, 100), "2": (0, 3)},
    )
    assert any(g.rule == "GL001" for g in fs), fs


def test_min_clamp_suppresses_upper_escape():
    def f(x):
        return jnp.minimum(x * 70001 * 70001, INF)

    fs = audit_fn(f, np.int32(3), seeds={"0": (0, 1 << 20)})
    # the inner mul feeds another mul (not a guard) and stays flagged;
    # the outer one feeds min and is suppressed
    outer_flagged = [g for g in fs if g.rule == "GL001"]
    assert len(outer_flagged) == 1, fs


def test_min_guard_does_not_excuse_lower_escape():
    """A `min` consumer re-bounds only the upper escape; a product
    whose interval also wraps below INT32_MIN must stay flagged (each
    escaping side needs its own guard)."""

    def f(x):
        return jnp.minimum(x * 70001 * 70001, INF)

    fs = audit_fn(f, np.int32(3), seeds={"0": (-(1 << 20), 1 << 20)})
    # both muls escape both sides; neither is fully guarded
    assert len([g for g in fs if g.rule == "GL001"]) == 2, [
        g.render() for g in fs
    ]


def test_one_hot_masked_merge_adds_exempt():
    """oh_pack_pairs' disjoint masked merges (`where(lo_hit, a, 0) +
    where(hi_hit, b, 0)`, `pay + sum` onto zero slots) are trusted to
    the one-hot contract even with INF-scale operands."""
    from fantoch_tpu.engine import core

    def f(pay, lo, a, b):
        return core.oh_pack_pairs(pay, lo, a, b)

    fs = audit_fn(
        f,
        np.zeros((8,), np.int32), np.zeros((2,), np.int32),
        np.zeros((2,), np.int32), np.zeros((2,), np.int32),
        seeds={"0": (0, INF), "1": (0, 8), "2": (0, INF), "3": (0, INF)},
    )
    assert [g for g in fs if g.rule == "GL001"] == [], [
        g.render() for g in fs
    ]


def test_one_hot_fn_affine_math_still_checked():
    """Dropping the sentinel clamp inside a ONE_HOT_FNS packer must
    still flag — the one-hot trust covers only masked reductions and
    merges, never the affine packing muls/adds (the _pack_deps
    regression class)."""

    def _pack_deps(pay, lo_base, order):
        lo = lo_base + 3 * order  # unclamped: order can carry INF
        iota = jnp.arange(pay.shape[0], dtype=I32)
        oh = lo[:, None] == iota[None, :]
        return pay + jnp.sum(
            jnp.where(oh, order[:, None], 0), axis=0, dtype=I32
        )

    fs = audit_fn(
        _pack_deps,
        np.zeros((8,), np.int32), np.int32(0), np.zeros((2,), np.int32),
        seeds={"0": (0, 100), "1": (0, 8), "2": (0, INF)},
    )
    assert any(g.rule == "GL001" for g in fs), fs


def test_state_escape_is_not_guarded():
    """A wrapped value that *also* lands raw in the jaxpr's outputs
    (carried state) stays flagged even though its other consumer is a
    clamp — the clamp cannot re-bound the stored copy."""

    def f(x):
        big = x * 70001
        return big, jnp.minimum(big, INF)

    fs = audit_fn(f, np.int32(3), seeds={"0": (0, 1 << 20)})
    assert any(g.rule == "GL001" and ":mul" in g.anchor for g in fs), fs


def test_f32_matmul_exactness_gl002():
    def f(x):
        tri = jnp.triu(jnp.ones((8, 8), jnp.float32))
        return (x.astype(jnp.float32) @ tri).astype(I32)

    big = audit_fn(
        f, np.zeros((8,), np.int32), seeds={"0": (0, 1 << 23)}
    )
    assert any(g.rule == "GL002" for g in big), big
    small = audit_fn(
        f, np.zeros((8,), np.int32), seeds={"0": (0, 1 << 10)}
    )
    assert not any(g.rule == "GL002" for g in small), small


def test_cumsum_i32_static_exactness_guard():
    # bool masks keep the single-matmul path
    jx = jax.make_jaxpr(cumsum_i32)(np.ones((16,), bool))
    assert any(e.primitive.name == "dot_general" for e in jx.eqns)
    # non-bool without a bound: loud trace-time error, never wrong sums
    with pytest.raises(TypeError, match="bound"):
        cumsum_i32(jnp.ones((16,), I32))
    # a bound that breaks f32 exactness falls back to the stock cumsum
    jx = jax.make_jaxpr(
        lambda x: cumsum_i32(x, bound=1 << 22)
    )(np.ones((16,), np.int32))
    assert not any(e.primitive.name == "dot_general" for e in jx.eqns)


# ----------------------------------------------------------------------
# alpha-equivalence units
# ----------------------------------------------------------------------


def _jx(f, *args):
    return jax.make_jaxpr(f)(*args)


def test_alpha_equivalent_renamed_vars():
    def f(x, y):
        a = x + y
        return a * 2

    def g(p, q):  # same graph, different python names
        fresh = p + q
        return fresh * 2

    ok, why = alpha_equivalent(
        _jx(f, np.int32(1), np.int32(2)), _jx(g, np.int32(1), np.int32(2))
    )
    assert ok, why


def test_alpha_diff_on_constant_and_primitive():
    x = np.int32(1)
    ok, why = alpha_equivalent(
        _jx(lambda v: v * 2, x), _jx(lambda v: v * 3, x)
    )
    assert not ok and "literal" in why, why
    ok, why = alpha_equivalent(
        _jx(lambda v: v * 2, x), _jx(lambda v: v + 2, x)
    )
    assert not ok and "primitive" in why, why
    ok, why = alpha_equivalent(
        _jx(lambda v: v * 2, x), _jx(lambda v: (v * 2) + 0 * v, x)
    )
    assert not ok, "extra equations must not be equivalent"
    # output arity: a dropped (or leaked) output that adds no equation
    # must still diff — it changes what the step carries
    ok, why = alpha_equivalent(
        _jx(lambda v: (v * 2, v), x), _jx(lambda v: (v * 2,), x)
    )
    assert not ok and "outvar" in why, why


def test_audit_fn_const_lhs_matmul():
    """A host-side constant matrix as the dot lhs (the constant-hoisted
    cumsum_i32 form) must audit, not crash _contract_count."""
    tri = np.triu(np.ones((4, 4), np.float32))

    def f(x):
        return (tri @ x.astype(np.float32)).astype(np.int32)

    fs = audit_fn(f, np.zeros((4,), np.int32), seeds={"0": (0, 100)})
    assert [g.rule for g in fs] in ([], ["GL002"]), fs


# ----------------------------------------------------------------------
# seeded regressions (the CI contract)
# ----------------------------------------------------------------------


def _basic_lane(dev, monitor_keys=0):
    n, clients, commands = 3, 3, 2
    config = Config(**dev_config_kwargs("basic", n, 1))
    planet = Planet.new()
    regions = planet.regions()[:n]
    total = commands * clients
    dims = EngineDims.for_protocol(
        dev, n=n, clients=clients, payload=dev.payload_width(n),
        total_commands=total, dot_slots=total + 1, regions=n,
    )
    spec = make_lane(
        dev, planet, config, conflict_rate=100, pool_size=1,
        commands_per_client=commands, clients_per_region=1,
        process_regions=regions, client_regions=regions, dims=dims,
    )
    st = init_lane_state(dev, dims, spec.ctx, monitor_keys=monitor_keys)
    return dims, spec, st


class OverflowDev(BasicDev):
    """Seeded regression: an unclamped i32 multiply on a sequence
    counter, reachable from the protocol step."""

    @staticmethod
    def periodic(ps, fire, me, now, ctx, dims):
        ps, ob = BasicDev.periodic(ps, fire, me, now, ctx, dims)
        return dict(ps, own_seq=ps["own_seq"] * 70001), ob


def test_auditor_catches_seeded_overflow_mul():
    dims, spec, st = _basic_lane(OverflowDev)
    trace = trace_step(OverflowDev, dims, st, spec.ctx, name="seeded")
    fs = audit_trace(trace)
    hits = [
        g for g in fs if g.rule == "GL001" and ":periodic:mul" in g.anchor
    ]
    assert hits, [g.render() for g in fs]
    # the same lane through the clean protocol has no periodic finding
    dims, spec, st = _basic_lane(BasicDev)
    clean = audit_trace(
        trace_step(BasicDev, dims, st, spec.ctx, name="clean")
    )
    assert not any(":periodic:" in g.anchor for g in clean), clean


class NoHooksDev:
    """Seeded regression: protocol registered without its hooks."""

    MONITORED = True  # claims monitors but this module never calls
    # mon_exec, and there is no min_live


def test_hook_rule_catches_missing_registration():
    fs = check_protocol_hooks([("nohooks", NoHooksDev)])
    kinds = {g.anchor.rsplit(":", 1)[1] for g in fs}
    assert "min_live" in kinds, fs
    assert "mon_exec" in kinds, fs

    class Undeclared:
        @staticmethod
        def min_live(config):
            return config.n - config.f

    fs = check_protocol_hooks([("undeclared", Undeclared)])
    assert any(g.anchor.endswith(":MONITORED") for g in fs), fs


def test_registry_hooks_clean_at_head():
    assert check_protocol_hooks() == []


# ----------------------------------------------------------------------
# AST rules
# ----------------------------------------------------------------------


def test_ast_rules_flag_fixture():
    fs = run_ast_rules([FIXTURE])
    rules = {g.rule for g in fs}
    assert {"GL101", "GL103", "GL104"} <= rules, [g.render() for g in fs]


def test_ast_rules_clean_at_head():
    assert run_ast_rules() == [], [
        g.render() for g in run_ast_rules()
    ]


def test_outbox_dict_constructor_flagged(tmp_path):
    """GL101 must also catch the dict() spelling of a raw outbox."""
    path = tmp_path / "proto_bad.py"
    path.write_text(
        "def handle(ps, msg):\n"
        "    return dict(valid=v, dst=d, mtype=t, payload=p)\n"
    )
    fs = run_ast_rules([str(path)])
    assert any(g.rule == "GL101" for g in fs), [g.render() for g in fs]


# ----------------------------------------------------------------------
# audits vs the checked-in baseline + gating proof (one cheap protocol)
# ----------------------------------------------------------------------


def test_basic_audit_within_baseline_and_gated():
    dims, spec, st = _basic_lane(BasicDev)
    trace = trace_step(BasicDev, dims, st, spec.ctx, name="basic")
    fs = audit_trace(trace)
    allowed = set(load_baseline(DEFAULT_BASELINE))
    assert {g.id for g in fs} <= allowed, [g.render() for g in fs]
    assert check_gating(trace) == []


# ----------------------------------------------------------------------
# the CI entrypoint
# ----------------------------------------------------------------------


def test_cli_lint_broken_fixture_exits_nonzero(capsys):
    from fantoch_tpu import cli

    with pytest.raises(SystemExit) as e:
        cli.main(
            ["lint", "--no-jaxpr", "--paths", FIXTURE, "--baseline"]
        )
    assert e.value.code == 1
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["regressions"] > 0


def test_cli_lint_clean_ast_exits_zero(capsys):
    from fantoch_tpu import cli

    cli.main(["lint", "--no-jaxpr", "--baseline"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["regressions"] == 0


def test_cli_write_baseline_refuses_narrowed_run():
    """A run missing whole audit classes must not clobber the
    checked-in baseline (every skipped finding would become a CI
    regression on the next full run)."""
    from fantoch_tpu import cli

    with pytest.raises(SystemExit) as e:
        cli.main(["lint", "--no-jaxpr", "--write-baseline"])
    assert "narrowed" in str(e.value.code)


def test_load_baseline_plain_map(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"_comment": "x", "GL001:a:b:mul": 2}))
    assert load_baseline(str(path)) == {"GL001:a:b:mul": 2}

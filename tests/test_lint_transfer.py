"""Transfer-family tests (fantoch_tpu/lint/transfer.py + alias.py):
GL301 sync-taxonomy and loop-tier classification units on synthetic
sources, the ledger regression gate, GL302 donation-lifetime prover
units (use-after-donate, rebind idiom, device-state saves, AOT gate),
GL303 backend-width audit, clean-at-HEAD pins, the seeded CI
self-checks, and the GL1xx scan-set coverage self-test — all pure
AST/arithmetic, no device and no tracing."""

import json
import os
import textwrap

import pytest

from fantoch_tpu.lint.alias import run_alias
from fantoch_tpu.lint.transfer import (
    DEFAULT_TRANSFER_BASELINE,
    backend_audit,
    gate_backend,
    gate_ledger,
    ledger_summary,
    load_transfer_baseline,
    run_transfer,
    run_transfer_selfcheck,
    scan_transfer,
    write_transfer_baseline,
)


def _scan(tmp_path, src, name="synth.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(src))
    return scan_transfer([str(path)])


def _sites(tmp_path, src):
    sites, findings = _scan(tmp_path, src)
    assert findings == [], [f.render() for f in findings]
    return sites


def _alias(tmp_path, src, name="synth.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(src))
    return run_alias([str(path)])


# ----------------------------------------------------------------------
# GL301: sync taxonomy
# ----------------------------------------------------------------------


def test_explicit_syncs_registered(tmp_path):
    sites = _sites(tmp_path, """
        import jax

        def drive(state):
            jax.block_until_ready(state)
            host = jax.device_get(state)
            return host
    """)
    kinds = sorted(s.kind for s in sites)
    assert kinds == ["block_until_ready", "device_get"]
    assert all(s.tier == "sweep" for s in sites)


def test_implicit_bool_coercion_of_device_value(tmp_path):
    sites = _sites(tmp_path, """
        from fantoch_tpu.engine.core import build_segment_runner

        def drive(state, ctx, until):
            runner, _ = build_segment_runner(state, ctx, 8)
            state, alive = runner(state, ctx, until)
            if bool(alive):
                return state
            return state
    """)
    assert [s.kind for s in sites] == ["bool"]


def test_item_only_flags_device_tracked_operands(tmp_path):
    # numpy shares .item()/.tolist() with device arrays: a host-side
    # serialization helper must NOT register (the results.py to_json
    # false-positive class), a runner output must
    sites = _sites(tmp_path, """
        from fantoch_tpu.engine.core import get_runner

        def to_json(host_arr):
            return host_arr.tolist()

        def drive(state, ctx, until):
            runner = get_runner(state)
            out = runner(state, ctx, until)
            return out["err"].item()
    """)
    assert [(s.fn, s.kind) for s in sites] == [("drive", "item")]


def test_host_fetch_launders_device_to_host(tmp_path):
    # after host_fetch the binding is host-side: .item() on it is free
    sites = _sites(tmp_path, """
        from fantoch_tpu.engine.core import get_runner, host_fetch

        def drive(state, ctx, until):
            runner = get_runner(state)
            out = runner(state, ctx, until)
            host = host_fetch(out, tier="sweep", reason="final fetch")
            return host["err"].item()
    """)
    assert [s.kind for s in sites] == ["host_fetch@sweep"]


# ----------------------------------------------------------------------
# GL301: loop-tier classification
# ----------------------------------------------------------------------

_TIER_SRC = """
    import jax

    def drive(state, untils):
        jax.block_until_ready(state)               # depth 0: sweep
        for until in untils:                       # depth 1: window
            jax.block_until_ready(state)
            if until > 0:                          # guarded: checkpoint
                jax.block_until_ready(state)
            for _ in range(8):                     # depth 2: segment
                jax.block_until_ready(state)
        return state
"""


def test_loop_depth_tier_classification(tmp_path):
    tiers = [s.tier for s in _sites(tmp_path, _TIER_SRC)]
    assert tiers == ["sweep", "window", "checkpoint", "segment"]


def test_tier_migration_regresses_against_baseline(tmp_path):
    # the four same-kind sites group into ONE ledger id whose tier is
    # the hottest observed ("segment")
    sites = _sites(tmp_path, _TIER_SRC)
    path = tmp_path / "base.json"
    write_transfer_baseline(str(path), sites)
    base = load_transfer_baseline(str(path))
    assert len(base) == 1 and next(iter(base.values()))["tier"] == "segment"
    ok, stale = gate_ledger(sites, base)
    assert ok == [] and stale == []
    # the same entry baselined colder: the hotter observed tier is a
    # migration regression even though the count is unchanged
    colder = {sid: dict(e, tier="window") for sid, e in base.items()}
    viol, _ = gate_ledger(sites, colder)
    assert len(viol) == 1 and "HOTTER" in viol[0].message


def test_new_sync_and_count_growth_regress(tmp_path):
    sites = _sites(tmp_path, """
        import jax

        def drive(state):
            jax.block_until_ready(state)
            jax.device_get(state)
    """)
    by_kind = {s.kind: s for s in sites}
    only_block = {
        by_kind["block_until_ready"].id: {
            "count": 1, "tier": "sweep", "reason": "pinned",
        }
    }
    viol, _ = gate_ledger(sites, only_block)
    assert [f.id for f in viol] == [by_kind["device_get"].id]
    grown = dict(only_block)
    grown[by_kind["device_get"].id] = {
        "count": 1, "tier": "sweep", "reason": "pinned",
    }
    ok, _ = gate_ledger(sites, grown)
    assert ok == []


def test_choke_call_requires_literal_metadata(tmp_path):
    _, findings = _scan(tmp_path, """
        from fantoch_tpu.engine.core import host_fetch

        def drive(state, tier):
            return host_fetch(state, tier=tier, reason="dynamic")
    """)
    assert len(findings) == 1
    assert "literal" in findings[0].message


def test_choke_tier_underclaim_refused(tmp_path):
    # declared "sweep" inside a depth-2 loop: the declaration
    # under-claims hotness, which would let a hot sync hide behind a
    # cold baseline entry
    _, findings = _scan(tmp_path, """
        from fantoch_tpu.engine.core import host_fetch

        def drive(state, untils):
            for until in untils:
                for _ in range(8):
                    state = host_fetch(state, tier="sweep", reason="x")
            return state
    """)
    assert len(findings) == 1
    assert "never hide" in findings[0].message


# ----------------------------------------------------------------------
# GL302: donation-lifetime prover
# ----------------------------------------------------------------------


def test_use_after_donate_flagged(tmp_path):
    fs = _alias(tmp_path, """
        from fantoch_tpu.engine.core import build_segment_runner

        def drive(state, ctx, until):
            runner, _ = build_segment_runner(state, ctx, 8)
            out, alive = runner(state, ctx, until)
            return out, state["clock"]
    """)
    assert len(fs) == 1 and fs[0].rule == "GL302"
    assert "use-after-donate" in fs[0].anchor


def test_donate_then_rebind_is_clean(tmp_path):
    # the engine's standard idiom: the donated binding is resurrected
    # by the very call that consumed it
    fs = _alias(tmp_path, """
        from fantoch_tpu.engine.core import build_segment_runner

        def drive(state, ctx, untils):
            runner, _ = build_segment_runner(state, ctx, 8)
            for until in untils:
                state, alive = runner(state, ctx, until)
            return state
    """)
    assert fs == [], [f.render() for f in fs]


def test_save_of_device_fresh_state_flagged(tmp_path):
    fs = _alias(tmp_path, """
        from fantoch_tpu.engine.checkpoint import save_boundary
        from fantoch_tpu.engine.core import build_segment_runner

        def drive(state, ctx, until):
            runner, _ = build_segment_runner(state, ctx, 8)
            state, alive = runner(state, ctx, until)
            save_boundary(state, until)
    """)
    assert [f.rule for f in fs] == ["GL302"]
    assert "save-device-state" in fs[0].anchor


def test_save_of_host_fetched_state_clean(tmp_path):
    fs = _alias(tmp_path, """
        from fantoch_tpu.engine.checkpoint import save_boundary
        from fantoch_tpu.engine.core import build_segment_runner, host_fetch

        def drive(state, ctx, until):
            runner, _ = build_segment_runner(state, ctx, 8)
            state, alive = runner(state, ctx, until)
            save_boundary(
                host_fetch(state, tier="checkpoint", reason="drain"),
                until,
            )
    """)
    assert fs == [], [f.render() for f in fs]


def test_aot_donate_without_gate_flagged(tmp_path):
    fs = _alias(tmp_path, """
        from fantoch_tpu.parallel import aot as aot_mod

        def drive(spec, sig, state):
            return aot_mod.get_runner(spec, sig, state=state, donate=True)
    """)
    assert [f.rule for f in fs] == ["GL302"]
    assert "aot-donate" in fs[0].anchor


def test_aot_donate_with_gate_clean(tmp_path):
    fs = _alias(tmp_path, """
        from fantoch_tpu.engine.core import aot_donation_safe
        from fantoch_tpu.parallel import aot as aot_mod

        def drive(spec, sig, state, donate):
            if not aot_donation_safe():
                donate = False
            return aot_mod.get_runner(spec, sig, state=state, donate=donate)
    """)
    assert fs == [], [f.render() for f in fs]


# ----------------------------------------------------------------------
# GL303: backend-width audit
# ----------------------------------------------------------------------


def test_backend_audit_names_known_gaps():
    ids = sorted(f.id for f in backend_audit())
    assert ids == [
        "GL303:backend:fantoch_tpu/engine/dims.py:cpu:kernel-ms-unmeasured",
        "GL303:backend:fantoch_tpu/engine/dims.py:gpu:kernel-ms-unmeasured",
        "GL303:backend:fantoch_tpu/engine/dims.py:gpu:matmul-exactness",
    ]


def test_backend_gate_clean_against_checked_in_baseline():
    viol, stale = gate_backend(load_transfer_baseline())
    assert viol == [] and stale == []


def test_backend_gate_flags_unbaselined_gap():
    base = {
        k: v
        for k, v in load_transfer_baseline().items()
        if "matmul-exactness" not in k
    }
    viol, _ = gate_backend(base)
    assert [f.id for f in viol] == [
        "GL303:backend:fantoch_tpu/engine/dims.py:gpu:matmul-exactness"
    ]


# ----------------------------------------------------------------------
# clean at HEAD: the ledger, the prover, the gate
# ----------------------------------------------------------------------


def test_transfer_clean_at_head():
    findings, summary = run_transfer()
    assert findings == [], [f.render() for f in findings]
    assert summary["stale_baseline"] == []
    assert summary["tiers"]["segment"] == 0, (
        "a per-segment sync crept into the host layers — docs/PERF.md"
    )


def test_alias_clean_at_head():
    fs = run_alias()
    assert fs == [], [f.render() for f in fs]


def test_head_ledger_matches_checked_in_baseline():
    """Every intentional sync at HEAD is named in the baseline with a
    justification, and the baseline carries no dead entries
    (regenerate with `lint --write-transfer-baseline` and review)."""
    sites, findings = scan_transfer()
    assert findings == []
    base = load_transfer_baseline()
    ids = {s.id for s in sites}
    baselined_301 = {k for k in base if k.startswith("GL301:")}
    assert ids == baselined_301
    assert all(base[k].get("reason") for k in base)


def test_write_transfer_baseline_roundtrip(tmp_path):
    sites, _ = scan_transfer()
    path = tmp_path / "transfer_baseline.json"
    write_transfer_baseline(str(path), sites)
    viol, stale = gate_ledger(sites, load_transfer_baseline(str(path)))
    assert viol == [] and stale == []


def test_ledger_summary_is_device_free():
    summary = ledger_summary()
    assert summary["sites"] == sum(summary["tiers"].values())
    assert summary["tiers"]["segment"] == 0


# ----------------------------------------------------------------------
# seeded CI self-checks + CLI plumbing
# ----------------------------------------------------------------------


def test_selfcheck_sync_regresses_gl301():
    fs = run_transfer_selfcheck("sync")
    assert fs and all(f.rule == "GL301" for f in fs), fs


def test_selfcheck_donate_regresses_gl302():
    fs = run_transfer_selfcheck("donate")
    assert fs and all(f.rule == "GL302" for f in fs), fs


def test_cli_selfchecks_exit_nonzero_and_name_rule(capsys):
    from fantoch_tpu import cli

    for kind, rule in (("sync", "GL301"), ("donate", "GL302")):
        with pytest.raises(SystemExit) as e:
            cli.main(["lint", "--transfer-selfcheck", kind])
        assert e.value.code == 1
        captured = capsys.readouterr()
        assert rule in captured.err
        out = json.loads(captured.out.strip().splitlines()[-1])
        assert out["regressions"] > 0


def test_cli_transfer_only_clean_at_head(capsys):
    from fantoch_tpu import cli

    cli.main(["lint", "--transfer-only", "--baseline"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["regressions"] == 0
    assert out["transfer"]["ids"] == out["transfer"]["sites"]


def test_cli_write_transfer_baseline_refuses_narrowed_run(tmp_path):
    from fantoch_tpu import cli

    fixture = os.path.join("tests", "fixtures", "transfer_bad_sync.py")
    with pytest.raises(SystemExit) as e:
        cli.main(
            [
                "lint",
                "--write-transfer-baseline",
                "--paths",
                fixture,
            ]
        )
    assert "narrowed" in str(e.value.code)


def test_write_baseline_never_swallows_transfer_findings(tmp_path):
    """GL3xx findings gate against transfer_baseline.json only — the
    main suppression baseline must never absorb them (report.py)."""
    from fantoch_tpu.lint.report import Finding, LintReport, write_baseline

    rep = LintReport()
    rep.extend(
        [
            Finding("GL301", "transfer", "a.py:f:item", "seeded"),
            Finding("GL101", "ast", "a.py:f:outbox", "kept"),
        ]
    )
    path = tmp_path / "baseline.json"
    write_baseline(str(path), rep)
    data = json.loads(path.read_text())["findings"]
    assert "GL101:ast:a.py:f:outbox" in data
    assert not any(k.startswith("GL3") for k in data)


# ----------------------------------------------------------------------
# scan-set coverage self-tests (satellite: registry-derived rule sets)
# ----------------------------------------------------------------------


def test_traced_scan_set_covers_every_jax_module():
    from fantoch_tpu.lint.rules import uncovered_traced_modules

    assert uncovered_traced_modules() == []


def test_traced_scan_set_detects_a_dropped_path():
    from fantoch_tpu.lint.rules import uncovered_traced_modules

    missing = uncovered_traced_modules(paths=("fantoch_tpu/engine/iset.py",))
    assert any("engine/core.py" in m for m in missing)


def test_transfer_scan_paths_exist():
    from fantoch_tpu.lint.rules import REPO_ROOT
    from fantoch_tpu.registry import TRANSFER_SCAN_PATHS

    for rel in TRANSFER_SCAN_PATHS:
        assert os.path.exists(os.path.join(REPO_ROOT, rel)), rel


def test_default_transfer_baseline_is_checked_in():
    assert os.path.exists(DEFAULT_TRANSFER_BASELINE)

"""Experiment-dir plot families: throughput-vs-latency + tables.

Synthesizes two experiment directories in the exact on-disk shape
``fantoch_tpu.exp.bench_experiment`` produces (exp_config.json,
client_*.json latency series, .metrics_process_* pickles, dstat.json)
and renders every family the reference's fantoch_plot ships for them
(lib.rs:500-626 throughput; lib.rs:1619-1974 tables).
"""

import json
import os
import pickle

from fantoch_tpu.core.metrics import Metrics
from fantoch_tpu.plot import (
    dstat_table,
    experiment_points,
    process_metrics_table,
    throughput_latency_plot,
)
from fantoch_tpu.protocol.base import ProtocolMetricsKind


def _fake_experiment(root, protocol, clients, lat_ms, batch=1, f=1,
                     shards=1, **extra):
    tag = "".join(f"_{k}{v}" for k, v in sorted(extra.items()))
    run_dir = os.path.join(
        root, f"{protocol}_f{f}_s{shards}_c{clients}_b{batch}{tag}"
    )
    os.makedirs(run_dir)
    with open(os.path.join(run_dir, "exp_config.json"), "w") as fh:
        json.dump(
            {
                "protocol": protocol,
                "n": 3,
                "f": f,
                "shard_count": shards,
                "clients": clients,
                "commands_per_client": 4,
                "conflict": 50,
                "extra": {"batch_max_size": batch, **extra},
            },
            fh,
        )
    lat_us = lat_ms * 1000
    with open(os.path.join(run_dir, "client_1.json"), "w") as fh:
        json.dump(
            {str(cid): [lat_us] * 4 for cid in range(1, clients + 1)}, fh
        )
    for pid in (1, 2, 3):
        pm = Metrics()
        pm.aggregate(ProtocolMetricsKind.FAST_PATH, clients * 4)
        pm.aggregate(ProtocolMetricsKind.STABLE, clients * 4)
        with open(
            os.path.join(run_dir, f".metrics_process_{pid}"), "wb"
        ) as fh:
            pickle.dump(
                {"process_id": pid, "shard_id": 0, "protocol": pm,
                 "executors": []},
                fh,
            )
    series = [
        {"time": float(t), "cpu_jiffies": 1000.0 + 240.0 * t,
         "memavailable": 800_000.0 - 20_000.0 * t}
        for t in range(4)
    ]
    series[0]["time"] = 0.0
    series[-1]["time"] = 2.5
    series[-1]["cpu_jiffies"] = 1600.0
    series[-1]["memavailable"] = 750_000.0
    with open(os.path.join(run_dir, "dstat.json"), "w") as fh:
        json.dump(
            {"start": series[0], "end": series[-1], "series": series},
            fh,
        )
    return run_dir


def test_throughput_latency_and_tables(tmp_path):
    dirs = [
        _fake_experiment(str(tmp_path), "tempo", 2, lat_ms=40),
        _fake_experiment(str(tmp_path), "tempo", 8, lat_ms=60),
        _fake_experiment(str(tmp_path), "atlas", 2, lat_ms=55),
    ]
    series = experiment_points(dirs)
    assert set(series) == {"tempo", "atlas"}
    assert len(series["tempo"]) == 2
    # closed loop: throughput = issued / mean client run time;
    # 2 clients × 4 cmds at 40 ms each → 8 / 0.16 s = 50 ops/s
    tp, lat = series["tempo"][0]
    assert lat == 40.0
    assert abs(tp - 50.0) < 1e-6
    # more clients, higher latency ⇒ curve bends right and up
    tp2, lat2 = series["tempo"][1]
    assert tp2 > tp and lat2 > lat

    png = str(tmp_path / "tp.png")
    throughput_latency_plot(series, png, title="tp vs lat")
    assert os.path.getsize(png) > 0

    table = dstat_table(dirs)
    assert "cpu (jiffies)" in table and "| 600 |" in table
    ptable = process_metrics_table(dirs)
    assert "| tempo n=3 f=1 | 1 | 8 | 0 | 8 |" in ptable


def test_heatmap_and_batching_families(tmp_path):
    from fantoch_tpu.plot import (
        batching_plot,
        batching_points,
        dstat_heatmap,
    )

    dirs = [
        _fake_experiment(str(tmp_path), "tempo", 4, lat_ms=50, batch=1),
        _fake_experiment(str(tmp_path), "tempo", 4, lat_ms=35, batch=4),
    ]
    png = str(tmp_path / "heat.png")
    dstat_heatmap(dirs, png, title="cpu utilization")
    assert os.path.getsize(png) > 0

    series = batching_points(dirs)
    (label,) = series  # one (protocol, clients, conflict) group
    assert label == "tempo n=3 c=4 r=50"
    assert [b for b, _, _ in series[label]] == [1, 4]
    # batching amortizes the round trip: lower latency, higher tput
    (_, tp1, lat1), (_, tp4, lat4) = series[label]
    assert lat4 < lat1 and tp4 > tp1
    png2 = str(tmp_path / "batch.png")
    batching_plot(series, png2, title="batching")
    assert os.path.getsize(png2) > 0


def test_intra_machine_scalability(tmp_path):
    """lib.rs:914-955: per cpu-count searches, max throughput over the
    matching runs (two client counts per cpu setting here)."""
    from fantoch_tpu.plot import (
        intra_machine_scalability_plot,
        intra_machine_scalability_points,
    )

    dirs = [
        _fake_experiment(str(tmp_path), "tempo", 2, lat_ms=40, cpus=1),
        _fake_experiment(str(tmp_path), "tempo", 8, lat_ms=50, cpus=1),
        _fake_experiment(str(tmp_path), "tempo", 2, lat_ms=20, cpus=2),
        _fake_experiment(str(tmp_path), "tempo", 8, lat_ms=25, cpus=2),
        # runs without a cpus axis are not part of this family
        _fake_experiment(str(tmp_path), "tempo", 8, lat_ms=25),
    ]
    series = intra_machine_scalability_points(dirs, n=3)
    (label,) = series
    assert label == "tempo r=50"
    assert [c for c, _ in series[label]] == [1, 2]
    # max over client counts at each cpu setting; halved latency
    # doubles closed-loop throughput
    (c1, tp1), (c2, tp2) = series[label]
    assert tp2 == 2 * tp1
    png = str(tmp_path / "intra.png")
    intra_machine_scalability_plot(series, png, title="intra")
    assert os.path.getsize(png) > 0


def test_inter_machine_scalability(tmp_path):
    """lib.rs:956-1010: grouped bars over (shard_count,
    keys_per_command, conflict) settings, one bar per protocol."""
    from fantoch_tpu.plot import inter_machine_scalability_plot

    dirs = [
        _fake_experiment(str(tmp_path), "tempo", 4, lat_ms=40, shards=1,
                         keys_per_command=1),
        _fake_experiment(str(tmp_path), "tempo", 4, lat_ms=60, shards=2,
                         keys_per_command=2),
        _fake_experiment(str(tmp_path), "atlas", 4, lat_ms=50, shards=1,
                         keys_per_command=1),
        _fake_experiment(str(tmp_path), "atlas", 4, lat_ms=80, shards=2,
                         keys_per_command=2),
    ]
    png = str(tmp_path / "inter.png")
    inter_machine_scalability_plot(dirs, n=3, path=png, title="inter")
    assert os.path.getsize(png) > 0


def test_cdf_split(tmp_path):
    """lib.rs:466-528: two stacked CDF panels sharing one x-axis
    (the reference contrasts f=1 against f=2)."""
    from fantoch_tpu.plot import cdf_plot_split

    top = [
        _fake_experiment(str(tmp_path), "tempo", 4, lat_ms=40, f=1),
        _fake_experiment(str(tmp_path), "atlas", 4, lat_ms=50, f=1),
    ]
    bottom = [
        _fake_experiment(str(tmp_path), "tempo", 4, lat_ms=90, f=2),
        _fake_experiment(str(tmp_path), "atlas", 4, lat_ms=110, f=2),
    ]
    png = str(tmp_path / "cdf_split.png")
    cdf_plot_split(top, bottom, png, title="f=1 vs f=2")
    assert os.path.getsize(png) > 0

"""Generic whole-protocol simulation test harness.

Mirrors the reference's ``sim_test`` (fantoch_ps/src/protocol/mod.rs:639-705)
and its checks:
- ``check_monitors`` (mod.rs:724-813): every process must record the exact
  same per-key execution order (linearizability-ish cross-replica check);
- ``check_metrics`` (mod.rs:815-879): all commands commit (leaderless), and
  all commands are GC'd at every process (n×commits for leaderless, (f+1)×
  for FPaxos).

Message reordering is enabled (delay ×U(0,10)) like the reference.

Scale matches the reference's sim_test — 10 clients per process × 100
commands (mod.rs:660) — reduced under the ``CI`` env var exactly like
the reference reduces its own load there (mod.rs:88-113).
"""

import os

from fantoch_tpu.client import ConflictPool, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.protocol.base import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

_CI = bool(os.environ.get("CI"))
COMMANDS_PER_CLIENT = 20 if _CI else 100
CLIENTS_PER_PROCESS = 3 if _CI else 10
KEY_GEN = ConflictPool(conflict_rate=50, pool_size=1)


def extract_process_metrics(metrics):
    def get(kind):
        return metrics.get_aggregated(kind) or 0

    return (
        get(ProtocolMetricsKind.FAST_PATH),
        get(ProtocolMetricsKind.SLOW_PATH),
        get(ProtocolMetricsKind.STABLE),
    )


def sim_test(
    protocol_cls,
    config: Config,
    commands_per_client: int = COMMANDS_PER_CLIENT,
    clients_per_process: int = CLIENTS_PER_PROCESS,
    seed: int = 0,
    extra_sim_time_ms: int = 10_000,
    reorder: bool = True,
) -> int:
    """Runs the protocol in the DES with reordering; returns the total slow
    path count after asserting the reference's invariants."""
    shard_count = 1
    config = config.with_(
        executor_monitor_execution_order=True,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        shard_count=shard_count,
    )

    planet = Planet.new()
    workload = Workload(
        shard_count=shard_count,
        key_gen=KEY_GEN,
        keys_per_command=2,
        commands_per_client=commands_per_client,
        payload_size=1,
    )
    regions = planet.regions()[: config.n]
    runner = Runner(
        protocol_cls,
        planet,
        config,
        workload,
        clients_per_process,
        regions,
        regions,
        seed=seed,
    )
    if reorder:
        runner.reorder_messages = True
    metrics, monitors, _latencies = runner.run(extra_sim_time_ms)

    per_process = {
        pid: extract_process_metrics(pm) for pid, (pm, _em) in metrics.items()
    }
    check_monitors(monitors)
    return check_metrics(
        config, commands_per_client, clients_per_process, per_process
    )


def check_monitors(monitors: dict) -> None:
    items = list(monitors.items())
    pid_a, monitor_a = items[0]
    assert monitor_a is not None, "execution order should be monitored"
    for pid_b, monitor_b in items[1:]:
        assert monitor_b is not None
        assert set(monitor_a.keys()) == set(monitor_b.keys()), (
            f"monitors of {pid_a} and {pid_b} should have the same keys"
        )
        for key in monitor_a.keys():
            order_a = monitor_a.get_order(key)
            order_b = monitor_b.get_order(key)
            assert len(order_a) == len(order_b), (
                f"key {key}: different execution counts on "
                f"{pid_a} ({len(order_a)}) vs {pid_b} ({len(order_b)})"
            )
            if order_a != order_b:
                first = next(
                    i for i in range(len(order_a)) if order_a[i] != order_b[i]
                )
                raise AssertionError(
                    f"different execution orders on key {key!r} at index"
                    f" {first}:\n  process {pid_a}: {order_a[first:first+5]}"
                    f"\n  process {pid_b}: {order_b[first:first+5]}"
                )


def check_metrics(
    config: Config,
    commands_per_client: int,
    clients_per_process: int,
    metrics: dict,
) -> int:
    total_fast = sum(m[0] for m in metrics.values())
    total_slow = sum(m[1] for m in metrics.values())
    total_stable = sum(m[2] for m in metrics.values())

    total_processes = config.n * config.shard_count
    total_clients = clients_per_process * total_processes
    min_total_commits = commands_per_client * total_clients
    max_total_commits = min_total_commits * config.shard_count

    if config.leader is None:
        total_commits = total_fast + total_slow
        assert min_total_commits <= total_commits <= max_total_commits, (
            f"number of committed commands out of bounds: {total_commits} not"
            f" in [{min_total_commits}, {max_total_commits}]"
        )

    gc_at = (config.f + 1) if config.leader is not None else config.n
    assert gc_at * min_total_commits == total_stable, (
        f"not all processes gced: expected {gc_at * min_total_commits},"
        f" got {total_stable}"
    )
    return total_slow
